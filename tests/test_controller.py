"""Controller protocol unit tests against the in-process fake world.

Covers the reference behaviors from controller.cc: readiness counting,
cross-rank consistency validation (mismatch → structured ERROR, never a
hang), fusion with look-ahead, response caching with bitvector sync, join
handling, and grouped collectives (SURVEY §2.1, §4).
"""
import numpy as np
import pytest

from horovod_tpu.common.message import (Request, RequestType, Response,
                                        ResponseType)
from horovod_tpu.common.dtypes import DataType

from util_world import InProcWorld, make_controller, run_ranks


def _allreduce_req(rank, name, shape=(4,), dtype=DataType.FLOAT32, **kw):
    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_type=dtype, tensor_name=name, tensor_shape=shape,
                   **kw)


def test_single_tensor_ready_when_all_ranks_submit():
    size = 3
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert len(rl.responses) == 1
        resp = rl.responses[0]
        assert resp.response_type == ResponseType.ALLREDUCE
        assert resp.tensor_names == ["t0"]
        assert resp.tensor_sizes == [4]


def test_tensor_not_ready_until_all_ranks():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step1(rank):
        ctrl = controllers[rank]
        if rank == 0:   # only rank 0 submits
            ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step1)
    assert all(len(rl.responses) == 0 for rl in results)

    def step2(rank):
        ctrl = controllers[rank]
        if rank == 1:   # now rank 1 catches up
            ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step2)
    for rl in results:
        assert [r.tensor_names for r in rl.responses] == [["t0"]]


def test_shape_mismatch_produces_error_response():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        shape = (4,) if rank == 0 else (5,)
        ctrl.tensor_queue.push_back_to_queue(
            _allreduce_req(rank, "bad", shape=shape))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert len(rl.responses) == 1
        assert rl.responses[0].response_type == ResponseType.ERROR
        assert "shape" in rl.responses[0].error_message.lower()


def test_dtype_mismatch_produces_error_response():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        dtype = DataType.FLOAT32 if rank == 0 else DataType.FLOAT64
        ctrl.tensor_queue.push_back_to_queue(
            _allreduce_req(rank, "bad", dtype=dtype))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert rl.responses[0].response_type == ResponseType.ERROR
        assert "data type" in rl.responses[0].error_message.lower()


def test_op_mismatch_produces_error_response():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        rtype = RequestType.ALLREDUCE if rank == 0 else RequestType.BROADCAST
        ctrl.tensor_queue.push_back_to_queue(
            Request(request_rank=rank, request_type=rtype,
                    tensor_name="bad", tensor_shape=(2,),
                    root_rank=0 if rtype == RequestType.BROADCAST else -1))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert rl.responses[0].response_type == ResponseType.ERROR


def test_fusion_merges_small_allreduces():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world,
                                   fusion_threshold=64 * 1024 * 1024)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        for i in range(5):
            ctrl.tensor_queue.push_back_to_queue(
                _allreduce_req(rank, f"g{i}", shape=(16,)))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert len(rl.responses) == 1
        assert rl.responses[0].tensor_names == [f"g{i}" for i in range(5)]
        assert rl.responses[0].tensor_sizes == [16] * 5


def test_fusion_respects_threshold():
    size = 2
    world = InProcWorld(size)
    # Threshold rounds to 128 bytes (atomic unit 64 × local_size 1):
    # fits exactly two 16-float tensors (64B each).
    controllers = [make_controller(r, size, world, fusion_threshold=128)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        for i in range(5):
            ctrl.tensor_queue.push_back_to_queue(
                _allreduce_req(rank, f"g{i}", shape=(16,)))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        sizes = [len(r.tensor_names) for r in rl.responses]
        assert sizes == [2, 2, 1]
        assert sum(sizes) == 5


def test_fusion_does_not_merge_different_dtypes():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world,
                                   fusion_threshold=1 << 20)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(
            _allreduce_req(rank, "f32", dtype=DataType.FLOAT32))
        ctrl.tensor_queue.push_back_to_queue(
            _allreduce_req(rank, "f64", dtype=DataType.FLOAT64))
        ctrl.tensor_queue.push_back_to_queue(
            _allreduce_req(rank, "f32b", dtype=DataType.FLOAT32))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        by_names = sorted(tuple(r.tensor_names) for r in rl.responses)
        # f32 + f32b fuse (look-ahead past f64); f64 stays alone
        assert by_names == [("f32", "f32b"), ("f64",)]


def test_response_cache_skips_negotiation_in_steady_state():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world, cache_capacity=64)
                   for r in range(size)]

    def cycle(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        return ctrl.compute_response_list()

    run_ranks(size, cycle)
    gathers_after_first = world.gather_count
    assert gathers_after_first > 0

    for _ in range(3):
        results = run_ranks(size, cycle)
        for rl in results:
            assert [r.tensor_names for r in rl.responses] == [["t0"]]
    # Steady state: no further RequestList gathers happened.
    assert world.gather_count == gathers_after_first


def test_cache_invalidated_on_shape_change():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world, cache_capacity=64)
                   for r in range(size)]

    def cycle_shape(shape):
        def _run(rank):
            ctrl = controllers[rank]
            ctrl.tensor_queue.push_back_to_queue(
                _allreduce_req(rank, "t0", shape=shape))
            return ctrl.compute_response_list()
        return _run

    run_ranks(size, cycle_shape((4,)))
    before = world.gather_count
    results = run_ranks(size, cycle_shape((8,)))   # same name, new shape
    assert world.gather_count > before              # forced renegotiation
    for rl in results:
        assert rl.responses[0].tensor_sizes == [8]


def test_join_counts_and_completes():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    # Rank 1 joins; rank 0 still allreduces: tensor is ready with 1 rank.
    def step1(rank):
        ctrl = controllers[rank]
        if rank == 0:
            ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        else:
            ctrl.tensor_queue.push_back_to_queue(
                Request(request_rank=rank, request_type=RequestType.JOIN,
                        tensor_name="__join__"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step1)
    for rl in results:
        assert [r.response_type for r in rl.responses] == \
            [ResponseType.ALLREDUCE]

    # Rank 0 joins too: JOIN response emitted for everyone.
    def step2(rank):
        ctrl = controllers[rank]
        if rank == 0:
            ctrl.tensor_queue.push_back_to_queue(
                Request(request_rank=rank, request_type=RequestType.JOIN,
                        tensor_name="__join__"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step2)
    for rl in results:
        assert [r.response_type for r in rl.responses] == [ResponseType.JOIN]
        assert rl.responses[0].last_joined_rank == 1


def test_allgather_with_join_is_error():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        if rank == 0:
            ctrl.tensor_queue.push_back_to_queue(
                Request(request_rank=rank,
                        request_type=RequestType.ALLGATHER,
                        tensor_name="g", tensor_shape=(2, 3)))
        else:
            ctrl.tensor_queue.push_back_to_queue(
                Request(request_rank=rank, request_type=RequestType.JOIN,
                        tensor_name="__join__"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert rl.responses[0].response_type == ResponseType.ERROR
        assert "join" in rl.responses[0].error_message.lower()


def test_allgather_variable_first_dim():
    size = 3
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(
            Request(request_rank=rank, request_type=RequestType.ALLGATHER,
                    tensor_name="g", tensor_shape=(rank + 1, 7)))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        resp = rl.responses[0]
        assert resp.response_type == ResponseType.ALLGATHER
        assert resp.tensor_sizes == [1, 2, 3]


def _queued_allgather(ctrl, rank, name, dim0, rest=(2,)):
    """Enqueue an allgather with a REAL tensor entry (fusion sizing needs
    the trailing dims via the tensor queue, reference controller.cc:917)."""
    from horovod_tpu.common.tensor_queue import TensorTableEntry
    tensor = np.zeros((dim0,) + rest, np.float32)
    entry = TensorTableEntry(tensor_name=name, tensor=tensor)
    ctrl.tensor_queue.add_to_tensor_queue(
        entry,
        Request(request_rank=rank, request_type=RequestType.ALLGATHER,
                tensor_type=DataType.FLOAT32, tensor_name=name,
                tensor_shape=tuple(tensor.shape)))


def test_fusion_merges_small_allgathers():
    """Allgather responses fuse like the reference's (controller.cc
    FuseResponses ALLGATHER branch): one world_size block of per-rank
    first dims per entry (message.cc:380-388), sized by OUTPUT bytes."""
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world,
                                   fusion_threshold=64 * 1024 * 1024)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        for i in range(3):
            _queued_allgather(ctrl, rank, f"a{i}", dim0=rank + 1)
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert len(rl.responses) == 1
        resp = rl.responses[0]
        assert resp.response_type == ResponseType.ALLGATHER
        assert resp.tensor_names == ["a0", "a1", "a2"]
        assert resp.tensor_sizes == [1, 2] * 3   # per-entry rank blocks


def test_allgather_fusion_sized_by_output_bytes():
    """The fusion threshold counts allgather OUTPUT bytes (sum of all
    ranks' first dims × trailing elems), not the local payload: three
    256-byte-output tensors against a 512-byte threshold fuse 2+1."""
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world, fusion_threshold=512)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        for i in range(3):
            # output = (8+8 rows) × 4 elems × 4 B = 256 B per tensor
            _queued_allgather(ctrl, rank, f"b{i}", dim0=8, rest=(4,))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        counts = [len(r.tensor_names) for r in rl.responses]
        assert counts == [2, 1], counts


def test_allgather_does_not_fuse_with_allreduce():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world,
                                   fusion_threshold=64 * 1024 * 1024)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "r0"))
        _queued_allgather(ctrl, rank, "g0", dim0=2)
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "r1"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        kinds = sorted((r.response_type.name, len(r.tensor_names))
                       for r in rl.responses)
        # The two allreduces fuse (look-ahead past the allgather); the
        # allgather stays its own response.
        assert kinds == [("ALLGATHER", 1), ("ALLREDUCE", 2)], kinds


def test_broadcast_root_mismatch_is_error():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(
            Request(request_rank=rank, request_type=RequestType.BROADCAST,
                    tensor_name="b", tensor_shape=(2,), root_rank=rank))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert rl.responses[0].response_type == ResponseType.ERROR
        assert "root" in rl.responses[0].error_message.lower()


def test_grouped_tensors_wait_for_all_members():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world,
                                   fusion_threshold=1 << 20)
                   for r in range(size)]
    for ctrl in controllers:
        gid = ctrl.group_table.register_group(["ga", "gb"])
        assert gid == 0

    def step1(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "ga"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step1)
    assert all(len(rl.responses) == 0 for rl in results)   # gb missing

    def step2(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "gb"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step2)
    for rl in results:
        assert len(rl.responses) == 1
        assert sorted(rl.responses[0].tensor_names) == ["ga", "gb"]


def test_shutdown_propagates():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        # only rank 1 requests shutdown; everyone must see it
        return controllers[rank].compute_response_list(
            shutdown_requested=(rank == 1))

    results = run_ranks(size, step)
    assert all(rl.shutdown for rl in results)


def test_arrival_order_is_deterministic():
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world, fusion_threshold=0)
                   for r in range(size)]

    def step(rank):
        ctrl = controllers[rank]
        # ranks submit in different local order; response order must match
        names = ["x", "y", "z"] if rank == 0 else ["z", "y", "x"]
        for n in names:
            ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, n))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    orders = [[r.tensor_names[0] for r in rl.responses] for rl in results]
    assert orders[0] == orders[1]   # identical order on every rank


def test_cached_responses_fuse_without_corrupting_cache():
    """Regression: fusing cache-served responses must not mutate the cached
    entries (they were corrupted in place, growing every cycle)."""
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world, cache_capacity=64,
                                   fusion_threshold=1 << 20)
                   for r in range(size)]

    def submit(rank, names):
        ctrl = controllers[rank]
        for n in names:
            ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, n))
        return ctrl.compute_response_list()

    run_ranks(size, lambda r: submit(r, ["x"]))        # x negotiated+cached
    run_ranks(size, lambda r: submit(r, ["y"]))        # y negotiated+cached
    for _ in range(5):
        results = run_ranks(size, lambda r: submit(r, ["x", "y"]))
        for rl in results:
            assert len(rl.responses) == 1               # fused from cache
            assert sorted(rl.responses[0].tensor_names) == ["x", "y"]
            assert rl.responses[0].tensor_sizes == [4, 4]


def test_joined_rank_does_not_block_cached_collectives():
    """Regression: with the cache enabled, a joined rank must assert all
    active cache bits so remaining ranks' cached collectives keep flowing."""
    size = 2
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world, cache_capacity=64)
                   for r in range(size)]

    def warm(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        return ctrl.compute_response_list()

    run_ranks(size, warm)   # negotiate + cache
    run_ranks(size, warm)   # steady state

    def rank1_joins(rank):
        ctrl = controllers[rank]
        if rank == 0:
            ctrl.tensor_queue.push_back_to_queue(_allreduce_req(rank, "t0"))
        else:
            ctrl.tensor_queue.push_back_to_queue(
                Request(request_rank=rank, request_type=RequestType.JOIN,
                        tensor_name="__join__"))
        return ctrl.compute_response_list()

    results = run_ranks(size, rank1_joins)
    # Rank 0's cached allreduce must have been served this very cycle.
    for rl in results:
        assert any(resp.response_type == ResponseType.ALLREDUCE and
                   resp.tensor_names == ["t0"] for resp in rl.responses), \
            [r.response_type for r in rl.responses]


def test_tuned_params_propagate_to_all_ranks():
    """Autotuned (fusion threshold, cycle time) stamped by the coordinator
    ride the broadcast ResponseList and are applied by EVERY rank on the
    same cycle (reference: Controller::SynchronizeParameters,
    controller.cc:39-53)."""
    size = 3
    world = InProcWorld(size)
    controllers = [make_controller(r, size, world) for r in range(size)]
    defaults = [c.tensor_fusion_threshold for c in controllers]
    controllers[0].pending_tuned_params = (5 * 1024 * 1024, 7.5)

    def step(rank):
        ctrl = controllers[rank]
        ctrl.tensor_queue.push_back_to_queue(
            _allreduce_req(rank, "tuned_t"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert rl.tuned_fusion_threshold == 5 * 1024 * 1024
        assert rl.tuned_cycle_time_ms == 7.5
    for ctrl, default in zip(controllers, defaults):
        assert ctrl.tensor_fusion_threshold == 5 * 1024 * 1024
        assert ctrl.tensor_fusion_threshold != default
    assert controllers[0].pending_tuned_params is None

    # Steady state (cache hits): a NEW proposal still forces one
    # negotiation cycle so it reaches everyone (controller.cc cache-state
    # coordination; controller.py:175-178).
    run_ranks(size, step)   # prime the cache
    controllers[0].pending_tuned_params = (9 * 1024 * 1024, 3.0)
    results = run_ranks(size, step)
    for ctrl in controllers:
        assert ctrl.tensor_fusion_threshold == 9 * 1024 * 1024
