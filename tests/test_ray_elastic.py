"""Ray elastic executor (VERDICT r1 item 8) with a stub ray module.

Reference: horovod/ray/elastic.py:38-465. ray is not installed in this
image (same as round 1's gated tests), so a minimal fake — actors are
threads, futures are events — drives the REAL ElasticDriver + registry +
RPC stack through the Ray bridge: discovery from cluster state, one actor
per slot, results collected rank-ordered.
"""
from __future__ import annotations

import sys
import threading
import types
from collections import OrderedDict

import pytest


# ---------------------------------------------------------------------------
# Minimal in-process ray
# ---------------------------------------------------------------------------
class _FakeFuture:
    def __init__(self, fn, args):
        self._result = None
        self._exc: BaseException | None = None
        self._done = threading.Event()

        def _run():
            try:
                self._result = fn(*args)
            except BaseException as e:  # noqa: BLE001
                self._exc = e
            finally:
                self._done.set()

        threading.Thread(target=_run, daemon=True).start()

    def result(self, timeout=60):
        assert self._done.wait(timeout), "fake ray task hung"
        if self._exc is not None:
            raise self._exc
        return self._result


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *args):
        return _FakeFuture(self._fn, args)


class _FakeActor:
    def __init__(self, cls):
        inst = cls()
        self.run = _FakeMethod(inst.run)


class _FakeActorFactory:
    def __init__(self, cls):
        self._cls = cls
        self.last_options: dict = {}

    def options(self, **kwargs):
        self.last_options = kwargs
        return self

    def remote(self, *a, **k):
        return _FakeActor(self._cls)


def _fake_ray(nodes):
    ray = types.ModuleType("ray")
    ray.nodes = lambda: nodes
    ray.remote = lambda cls=None, **kw: (
        _FakeActorFactory(cls) if cls is not None
        else (lambda c: _FakeActorFactory(c)))
    ray.get = lambda fut, **kw: fut.result()
    ray.kill = lambda actor, no_restart=True: None
    return ray


def _worker_fn():
    # Runs inside a fake actor (a thread). The env contract was applied
    # by the bridge before this call.
    return "ok"


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------
def test_ray_host_discovery_from_cluster_state(monkeypatch):
    nodes = [
        {"Alive": True, "NodeManagerHostname": "node-a",
         "Resources": {"CPU": 4.0, "GPU": 2.0}},
        {"Alive": True, "NodeManagerHostname": "node-b",
         "Resources": {"CPU": 2.0}},
        {"Alive": False, "NodeManagerHostname": "node-dead",
         "Resources": {"CPU": 8.0}},
    ]
    monkeypatch.setitem(sys.modules, "ray", _fake_ray(nodes))
    from horovod_tpu.ray.elastic import RayHostDiscovery

    cpu = RayHostDiscovery(cpus_per_slot=2)
    assert cpu.find_available_hosts_and_slots() == OrderedDict(
        [("node-a", 2), ("node-b", 1)])

    gpu = RayHostDiscovery(use_gpu=True, cpus_per_slot=1, gpus_per_slot=1)
    assert gpu.find_available_hosts_and_slots() == OrderedDict(
        [("node-a", 2)])


def test_elastic_ray_executor_runs_to_completion(monkeypatch):
    monkeypatch.setitem(sys.modules, "ray", _fake_ray([]))
    from horovod_tpu.elastic.discovery import FixedHostDiscovery
    from horovod_tpu.ray.elastic import ElasticRayExecutor

    discovery = FixedHostDiscovery(
        OrderedDict([("localhost", 1), ("127.0.0.1", 1)]))
    executor = ElasticRayExecutor(
        min_np=2, max_np=2, elastic_timeout=30.0,
        override_discovery=discovery)
    executor._pin_by_node = False     # fake cluster has no node resources
    executor.start()
    try:
        results = executor.run(_worker_fn)
    finally:
        executor.shutdown()
    assert results == ["ok", "ok"]


def test_elastic_ray_executor_requires_ray_at_run():
    """Importing the module and constructing the executor must not need
    ray; only starting actors does (gate parity with round 1)."""
    import horovod_tpu.ray as hray

    assert hasattr(hray, "ElasticRayExecutor")
    assert hasattr(hray, "RayHostDiscovery")
    try:
        import ray  # noqa: F401
        pytest.skip("ray installed; gate not applicable")
    except ImportError:
        pass
