"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference strategy of running "multi-node" tests as multiple
local processes (SURVEY §4): SPMD sharding tests use
--xla_force_host_platform_device_count=8, and multi-process controller
tests spawn real subprocesses on localhost.

Note: a sitecustomize may import jax at interpreter startup (e.g. the
axon TPU tunnel), so env vars alone are too late — we also flip the jax
config before any backend initializes.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
