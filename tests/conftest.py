"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference strategy of running "multi-node" tests as multiple
local processes (SURVEY §4): SPMD sharding tests use
--xla_force_host_platform_device_count=8, and multi-process controller
tests spawn real subprocesses on localhost.

Note: a sitecustomize may import jax at interpreter startup (e.g. the
axon TPU tunnel), so env vars alone are too late — we also flip the jax
config before any backend initializes.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Spawned worker subprocesses must honor JAX_PLATFORMS=cpu even when an
# environment sitecustomize force-registers an accelerator plugin at
# interpreter start (see tests/_cpusite/sitecustomize.py): put the shim
# first on PYTHONPATH so every child imports it instead.
_shim_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "_cpusite")
_pp = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and p != _shim_dir)   # re-prepend even if present: position wins
os.environ["PYTHONPATH"] = (_shim_dir + os.pathsep + _pp if _pp
                            else _shim_dir)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compile cache: the suite's wall clock is dominated by
# XLA-CPU compiles of the model-train-step tests (Inception train step
# alone ~200 s cold, ~24 s warm); repeat runs on one box hit the disk
# cache and skip them.  Set through the environment (not jax.config) so
# every spawned worker subprocess — multiprocess batteries, estimators,
# multihost tests — inherits it.  Opt out with
# HOROVOD_TEST_COMPILE_CACHE=0 (e.g. when bisecting a compiler issue).
if os.environ.get("HOROVOD_TEST_COMPILE_CACHE", "1") != "0":
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/horovod_tpu_test_jax_cache")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "2.0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES",
                          "-1")

# Flight-recorder failure dumps (HOROVOD_FLIGHT, on by default) resolve
# relative to the cwd: point them at /tmp so a fault-injection test can
# never litter the repo working tree.  Tests that assert on dumps set
# their own explicit paths (and inherit this default in workers).
os.environ.setdefault("HOROVOD_FLIGHT_FILE",
                      "/tmp/horovod_tpu_test_flight.json")

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        # The env var is read at jax import in recent versions; set the
        # config explicitly too (from the env values, which setdefault
        # left user-overridable) in case a sitecustomize imported jax
        # before this file ran.
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(os.environ.get(
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2.0")))
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            int(os.environ.get(
                "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")))
except ImportError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
