"""Minimal numpy-backed mxnet emulation for exercising the
horovod_tpu.mxnet binding without the (EOL, uninstallable) real package —
the same stub-module pattern as test_ray_elastic's fake ray.

Models the exact API slice the binding touches: NDArray (asnumpy, slice
assignment, dtype), optimizer.Optimizer/SGD with rescale_grad + update(),
gluon.Parameter (data/list_grad/grad_req) and gluon.Trainer whose
``step(batch_size)`` sets ``rescale_grad = _scale / batch_size``, calls
``_allreduce_grads()`` then updates — mirroring real gluon so the
DistributedTrainer averaging fold is tested against true semantics.
"""
from __future__ import annotations

import sys
import types

import numpy as np


class NDArray:
    def __init__(self, data, dtype=None):
        self._a = np.array(data, dtype=dtype)

    def asnumpy(self) -> np.ndarray:
        return self._a.copy()

    def __setitem__(self, key, value):
        self._a[key] = value._a if isinstance(value, NDArray) else value

    def __getitem__(self, key):
        return NDArray(self._a[key])

    @property
    def dtype(self):
        return self._a.dtype

    @property
    def shape(self):
        return self._a.shape


def _nd_array(data, dtype=None, ctx=None):
    return NDArray(data, dtype=dtype)


class Optimizer:
    def __init__(self, learning_rate=0.01, rescale_grad=1.0, **kwargs):
        self.lr = learning_rate
        self.rescale_grad = rescale_grad

    def create_state_multi_precision(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr


class SGD(Optimizer):
    def update(self, index, weight, grad, state):
        weight[:] = weight.asnumpy() - self.lr * self.rescale_grad \
            * grad.asnumpy()


class DeferredInitializationError(Exception):
    """Matched by type name in broadcast_parameters (real gluon raises
    mxnet.gluon.parameter.DeferredInitializationError)."""


class Parameter:
    def __init__(self, name, data=None, grad_req="write"):
        self.name = name
        self.grad_req = grad_req
        if data is None:        # deferred init: shape unknown until the
            self._data = None   # first forward infers it
        else:
            self._data = NDArray(data)
        self._grad = None if self._data is None else \
            NDArray(np.zeros_like(self._data.asnumpy()))

    def data(self):
        if self._data is None:
            raise DeferredInitializationError(self.name)
        return self._data

    def list_grad(self):
        return [self._grad]

    def list_data(self):
        return [self._data]

    def _init_impl(self, data):
        """Materialize a deferred param (real gluon calls this once the
        first forward has inferred shapes)."""
        self._data = NDArray(data)
        self._grad = NDArray(np.zeros_like(self._data.asnumpy()))


class Trainer:
    """Mirrors mx.gluon.Trainer's step contract (scale fold then reduce
    then update); kvstore push/pull is a no-op _allreduce_grads here."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        if hasattr(params, "values"):
            params = list(params.values())
        self._params = list(params)
        if isinstance(optimizer, str):
            optimizer = {"sgd": SGD}[optimizer](**(optimizer_params or {}))
        elif optimizer_params:
            for k, v in optimizer_params.items():
                setattr(optimizer, k, v)
        self._optimizer = optimizer
        self._scale = 1.0

    def _allreduce_grads(self):
        pass

    def step(self, batch_size):
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update()

    def _update(self):
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                self._optimizer.update(i, p.data(), p.list_grad()[0], None)


def install() -> types.ModuleType:
    """Register the stub as `mxnet` in sys.modules; returns the module."""
    mx = types.ModuleType("mxnet")
    mx.nd = types.ModuleType("mxnet.nd")
    mx.nd.array = _nd_array
    mx.nd.NDArray = NDArray
    mx.optimizer = types.ModuleType("mxnet.optimizer")
    mx.optimizer.Optimizer = Optimizer
    mx.optimizer.SGD = SGD
    mx.gluon = types.ModuleType("mxnet.gluon")
    mx.gluon.Trainer = Trainer
    mx.gluon.Parameter = Parameter
    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = mx.nd
    sys.modules["mxnet.optimizer"] = mx.optimizer
    sys.modules["mxnet.gluon"] = mx.gluon
    return mx
