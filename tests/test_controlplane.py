"""Coordinator-fault-tolerant control plane (ISSUE 15).

- WAL unit layer: record round-trip, torn-tail tolerance, replay
  digest, epoch fencing (a stale primary's post-promotion record is
  dropped).
- Durable rendezvous: a restarted server replays the log — puts,
  deletes and idempotent claims all survive coordinator death.
- Client: server-side long-poll (one outstanding request instead of a
  busy-poll), bounded idempotent retry across a restart window, bare
  claims fail fast, multi-endpoint failover + 409 leader redirects.
- Failover battery (in-process + subprocess primary): SIGKILL the
  primary -> the standby promotes within ~2x lease, clients converge,
  no committed write is lost (WAL replay digest-checked); SIGSTOP /
  SIGCONT (the coordpause split-brain shape) -> the resumed primary
  fences itself on the log's higher epoch and demotes.
- Versioned wire handshake: HELLO pack/negotiate units, the
  OPTIONAL_FIELD_FEATURES contract, and the mixed-proto world ridden
  end-to-end by the mp "rolling" battery.
"""
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402

from horovod_tpu.common import wire  # noqa: E402
from horovod_tpu.runner import controlplane as cp  # noqa: E402
from horovod_tpu.runner.network import (RendezvousClient,  # noqa: E402
                                        RendezvousServer, free_port)

LEASE_MS = 300.0


# --- WAL unit layer ---------------------------------------------------------
class TestWal:
    def test_record_roundtrip_and_digest(self, tmp_path):
        path = cp.wal_path(str(tmp_path))
        w = cp.WalWriter(path)
        assert w.append(1, "put", "s", "k", b"v")
        assert w.append(1, "claim", "s", "slots", b"h1|0")
        assert w.append(1, "delete", "s", "k", b"")
        w.close()
        recs = list(cp.replay(path))
        assert [(r[1], r[2], r[3]) for r in recs] == [
            ("put", "s", "k"), ("claim", "s", "slots"),
            ("delete", "s", "k")]
        state = cp.replay_state(path)
        assert state["kv"].get("s", {}) == {}
        assert state["counters"]["s/slots"] == 1
        assert state["claims"]["s/slots"] == {"h1": 0}

    def test_torn_tail_tolerated(self, tmp_path):
        path = cp.wal_path(str(tmp_path))
        w = cp.WalWriter(path)
        w.append(1, "put", "s", "a", b"1")
        w.append(1, "put", "s", "b", b"2")
        w.close()
        with open(path, "ab") as f:
            f.write(b"\x00\x00\x00\x20garbage-without-its-crc")
        state = cp.replay_state(path)
        assert state["kv"]["s"] == {"a": b"1", "b": b"2"}

    def test_epoch_fencing_drops_stale_primary_writes(self, tmp_path):
        """A write appended by a fenced-out stale primary (epoch 1
        record AFTER the epoch-2 leader record) is dropped by replay —
        the hazard the accept-stale-lease mutation makes reachable."""
        path = cp.wal_path(str(tmp_path))
        w = cp.WalWriter(path)
        w.append(1, "leader", "", "0", b"0|0")
        w.append(1, "put", "s", "committed", b"yes")
        w.append(2, "leader", "", "1", b"1|0")
        w.append(1, "put", "s", "stale", b"fenced-out")
        w.append(2, "put", "s", "new", b"ok")
        w.close()
        state = cp.replay_state(path)
        assert state["epoch"] == 2
        assert state["kv"]["s"] == {"committed": b"yes", "new": b"ok"}
        assert "stale" not in state["kv"]["s"]


# --- durable single server --------------------------------------------------
class TestDurableServer:
    def test_restart_replays_the_log(self, tmp_path):
        wal_dir = str(tmp_path)
        srv = RendezvousServer(wal_dir=wal_dir)
        srv.start()
        client = RendezvousClient("127.0.0.1", srv.port, timeout=10.0)
        client.put("mesh", "addr:0", b"10.0.0.1:4711")
        idx = client.claim("slots", "h1", task_key="h1[0]")
        client.put("mesh", "gone", b"x")
        client.delete("mesh", "gone")
        digest = srv.kv_digest()
        srv.stop()

        srv2 = RendezvousServer(wal_dir=wal_dir)
        srv2.start()
        c2 = RendezvousClient("127.0.0.1", srv2.port, timeout=10.0)
        assert c2.get("mesh", "addr:0") == b"10.0.0.1:4711"
        assert c2.get("mesh", "gone") is None
        # Idempotent claim re-present survives the restart.
        assert c2.claim("slots", "h1", task_key="h1[0]") == idx
        assert srv2.kv_digest() == digest
        # A fresh claimant gets the next index, not a reused one.
        assert c2.claim("slots", "h1", task_key="h1[1]") == idx + 1
        srv2.stop()

    def test_without_wal_dir_behavior_unchanged(self):
        srv = RendezvousServer()
        srv.start()
        assert srv.controlplane is None
        client = RendezvousClient("127.0.0.1", srv.port, timeout=5.0)
        client.put("s", "k", b"v")
        assert client.get("s", "k") == b"v"
        assert client.probe().startswith("primary")
        srv.stop()


# --- client behavior --------------------------------------------------------
class TestClient:
    def test_long_poll_wait_wakes_on_put(self):
        srv = RendezvousServer()
        srv.start()
        client = RendezvousClient("127.0.0.1", srv.port, timeout=10.0)

        def _put_later():
            time.sleep(0.3)
            srv.put("s", "slow", b"arrived")

        t = threading.Thread(target=_put_later)
        t0 = time.monotonic()
        t.start()
        value = client.wait("s", "slow", timeout=5.0)
        wall = time.monotonic() - t0
        t.join()
        assert value == b"arrived"
        # The long-poll held ONE request open and woke on the commit:
        # well under the old 10 ms busy-poll's worst case and far from
        # the 5 s deadline.
        assert 0.25 < wall < 2.0, wall
        srv.stop()

    def test_wait_times_out_bounded(self):
        srv = RendezvousServer()
        srv.start()
        client = RendezvousClient("127.0.0.1", srv.port, timeout=10.0)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            client.wait("s", "never", timeout=0.8)
        assert time.monotonic() - t0 < 3.0
        srv.stop()

    def test_idempotent_retry_rides_restart_window(self, tmp_path):
        """get/wait retry transient ECONNREFUSED inside one deadline —
        the coordinator-restart window — instead of raising raw
        URLError at the first refused connect."""
        wal_dir = str(tmp_path)
        srv = RendezvousServer(wal_dir=wal_dir)
        srv.start()
        port = srv.port
        srv.put("s", "k", b"v")
        srv.stop()

        client = RendezvousClient("127.0.0.1", port, timeout=8.0)

        def _restart_later():
            time.sleep(0.6)
            # Same port, WAL replayed: the restarted coordinator.
            back = RendezvousServer(port=port, wal_dir=wal_dir)
            back.start()
            self._restarted = back

        t = threading.Thread(target=_restart_later)
        t.start()
        value = client.get("s", "k")
        t.join()
        assert value == b"v"
        self._restarted.stop()

    def test_bare_claim_fails_fast_unreachable(self):
        port = free_port()
        client = RendezvousClient("127.0.0.1", port, timeout=5.0)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            client.claim("slots", "h1")          # no task_key: no retry
        assert time.monotonic() - t0 < 1.0
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            client.get("s", "k")                 # idempotent: bounded
        assert 4.0 < time.monotonic() - t0 < 8.0

    def test_seed_list_parsing(self):
        eps = RendezvousClient.parse_endpoints(
            "10.0.0.1:19000,10.0.0.2:19001", -1)
        assert eps == ["10.0.0.1:19000", "10.0.0.2:19001"]
        assert RendezvousClient.parse_endpoints("host", 80) == ["host:80"]


# --- in-process failover battery --------------------------------------------
class TestFailover:
    def test_standby_promotes_and_no_committed_write_lost(self, tmp_path):
        lease_s = LEASE_MS / 1e3
        servers, eps = cp.start_replica_set(2, str(tmp_path),
                                            lease_ms=LEASE_MS)
        try:
            client = RendezvousClient(",".join(eps), timeout=10.0)
            for i in range(8):
                client.put("s", f"k{i}", f"v{i}".encode())
            assert client.claim("slots", "h1", task_key="h1[0]") == 0
            digest = servers[0].kv_digest()
            assert cp.replay_state(cp.wal_path(str(tmp_path)))["digest"] \
                == digest

            # Hard-kill the primary (no graceful teardown).
            servers[0]._httpd.controlplane._stop.set()
            servers[0]._httpd.shutdown()
            servers[0]._httpd.server_close()

            t0 = time.monotonic()
            assert client.wait("s", "k3", timeout=10 * lease_s) == b"v3"
            wall = time.monotonic() - t0
            # Standby 1's lapse threshold is 2x lease (+ one monitor
            # interval of lease/3 detection granularity + client
            # backoff).
            assert wall < 3.5 * lease_s, wall

            standby = servers[1]
            assert standby.controlplane.role == "primary"
            assert standby.controlplane.failovers == 1
            assert standby.kv_digest() == digest
            # Idempotent claim answered by the NEW primary keeps the
            # original index; committed writes all survived.
            assert client.claim("slots", "h1", task_key="h1[0]") == 0
            for i in range(8):
                assert client.get("s", f"k{i}") == f"v{i}".encode()
            client.put("s", "post", b"after")
            assert client.get("s", "post") == b"after"
        finally:
            for s in servers[1:]:
                s.stop()


def _spawn_primary_subprocess(tmp_path, endpoints, lease_ms=LEASE_MS):
    """One replica as its own process (the chaos coordkill target)."""
    port = int(endpoints[0].rsplit(":", 1)[1])
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.controlplane",
         "--port", str(port), "--wal-dir", str(tmp_path),
         "--replica-id", "0", "--endpoints", ",".join(endpoints),
         "--lease-ms", str(lease_ms)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    line = proc.stdout.readline().decode()
    assert line.startswith("READY"), line
    return proc


class TestSubprocessPrimary:
    def _replica_pair(self, tmp_path):
        ports = [free_port(), free_port()]
        eps = [f"127.0.0.1:{p}" for p in ports]
        proc = _spawn_primary_subprocess(tmp_path, eps)
        standby = RendezvousServer(port=ports[1], wal_dir=str(tmp_path),
                                   replica_id=1, endpoints=eps,
                                   lease_ms=LEASE_MS, standby=True)
        standby.start()
        return proc, standby, eps

    def test_sigkill_primary_promotes_standby(self, tmp_path):
        proc, standby, eps = self._replica_pair(tmp_path)
        try:
            client = RendezvousClient(",".join(eps), timeout=15.0)
            client.put("s", "before", b"1")
            proc.kill()
            proc.wait(timeout=10)
            assert client.wait("s", "before",
                               timeout=10 * LEASE_MS / 1e3) == b"1"
            assert standby.controlplane.role == "primary"
            client.put("s", "after", b"2")
            # Quiescent now: the live digest equals a fresh replay of
            # the shared log — no committed write lost.
            assert standby.kv_digest() == cp.replay_state(
                cp.wal_path(str(tmp_path)))["digest"]
        finally:
            if proc.poll() is None:
                proc.kill()
            standby.stop()

    def test_coordpause_split_brain_fenced(self, tmp_path):
        """The lease-lapse-then-return shape (chaos ``coordpause:``):
        SIGSTOP the primary past its lease; the standby promotes; on
        SIGCONT the stale primary must fence itself on the log's
        higher leader epoch — demote to standby and redirect — never
        ack a write the replayed state would drop."""
        proc, standby, eps = self._replica_pair(tmp_path)
        try:
            client = RendezvousClient(",".join(eps), timeout=15.0)
            client.put("s", "pre-pause", b"1")
            os.kill(proc.pid, signal.SIGSTOP)
            # Past 2x lease the standby promotes.
            deadline = time.monotonic() + 10 * LEASE_MS / 1e3
            while standby.controlplane.role != "primary":
                assert time.monotonic() < deadline, "no promotion"
                time.sleep(0.05)
            client.put("s", "during-pause", b"2")
            os.kill(proc.pid, signal.SIGCONT)
            # The resumed primary re-verifies and demotes (proactively
            # from its lease loop, or at the first fenced write).
            old = RendezvousClient(eps[0], timeout=5.0)
            deadline = time.monotonic() + 10 * LEASE_MS / 1e3
            role = ""
            while time.monotonic() < deadline:
                role = old.probe() or ""
                if role.startswith("standby"):
                    break
                time.sleep(0.05)
            assert role.startswith("standby"), role
            # Writes through the seed list land on the promoted
            # standby; nothing committed was lost.
            seeded = RendezvousClient(",".join(eps), timeout=15.0)
            assert seeded.get("s", "pre-pause") == b"1"
            assert seeded.get("s", "during-pause") == b"2"
            seeded.put("s", "post-resume", b"3")
            assert standby.controlplane.role == "primary"
        finally:
            if proc.poll() is None:
                proc.kill()
            standby.stop()


# --- chaos coord actions ----------------------------------------------------
class TestChaosCoordActions:
    def test_parse_coord_actions(self):
        from horovod_tpu.resilience.chaos import parse_spec
        acts = parse_spec("coordkill:at=5;coordpause:at=7,ms=800,rank=1")
        kill, pause = acts
        assert kill.kind == "coordkill" and kill.op == 5
        assert kill.rank == 0 and kill.count == 1   # fires once, rank 0
        assert pause.kind == "coordpause" and pause.op == 7
        assert pause.ms == 800.0 and pause.rank == 1

    def test_coordkill_sigkills_the_primary(self, tmp_path, monkeypatch):
        port = free_port()
        eps = [f"127.0.0.1:{port}"]
        proc = _spawn_primary_subprocess(tmp_path, eps)
        try:
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR",
                               ",".join(eps))
            monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT",
                               str(port))
            from horovod_tpu.resilience.chaos import ChaosEngine
            eng = ChaosEngine("coordkill:at=2", rank=0)
            eng.on_response(["t0"])
            eng.on_response(["t1"])
            assert proc.poll() is None
            eng.on_response(["t2"])             # global index 2: fire
            proc.wait(timeout=10)
            assert proc.returncode == -signal.SIGKILL
        finally:
            if proc.poll() is None:
                proc.kill()


# --- versioned wire handshake ----------------------------------------------
class TestWireHandshake:
    def test_hello_roundtrip_and_negotiate(self):
        raw = wire.pack_hello(wire.PROTO_VERSION, wire.FEATURES_ALL)
        assert len(raw) == wire.HELLO_LEN
        assert wire.unpack_hello(raw) == (wire.PROTO_VERSION,
                                          wire.FEATURES_ALL)
        with pytest.raises(ValueError):
            wire.unpack_hello(b"\x00" * wire.HELLO_LEN)
        assert wire.negotiate(wire.PROTO_VERSION, wire.FEATURES_ALL,
                              wire.PROTO_VERSION, wire.FEATURES_ALL) \
            == (wire.PROTO_VERSION, wire.FEATURES_ALL)
        # A frozen proto's feature set does not grow with FEATURES_ALL:
        # two proto-2 peers negotiate the three-bit fp/tm/trace mask,
        # never the sharding bit proto 3 added.
        assert wire.negotiate(2, wire.FEATURES_ALL, 2,
                              wire.FEATURES_ALL) == \
            (2, wire.PROTO_FEATURE_SETS[2])
        assert not wire.PROTO_FEATURE_SETS[2] & wire.FEATURE_SHARDING
        # An old peer drags the pair to the base schema: features the
        # old proto cannot carry are masked even if advertised.
        assert wire.negotiate(2, wire.FEATURES_ALL, 1,
                              wire.FEATURES_ALL) == (1, 0)

    def test_optional_field_table_matches_analyzer_mirror(self):
        from horovod_tpu.analysis.hvdsan.san import \
            _OPTIONAL_WIRE_PREFIXES
        # Byte-for-byte: same prefixes, same order — a new group
        # appended to one table and not the other fails here before
        # any rolling upgrade can ship the skew.
        assert tuple(_OPTIONAL_WIRE_PREFIXES) == \
            tuple(wire.OPTIONAL_FIELD_FEATURES)
        # Every optional group vanishes from the wire when its bit is
        # negotiated away — and the base schema stays decodable.
        from horovod_tpu.common.message import RequestList, Response
        rl = RequestList(fp_seq=9, fp_digest=7, tm_cycles=3,
                         tm_cycle_ms=1.5)
        base = RequestList.from_bytes(rl.to_bytes(0), 0)
        assert base.fp_seq == 0 and base.tm_cycles == 0
        assert len(rl.to_bytes(0)) < len(rl.to_bytes())
        resp = Response(trace_cycle=4, trace_seq=2)
        assert len(_encode_response(resp, 0)) < \
            len(_encode_response(resp, wire.FEATURES_ALL))
        # The sp_* group rides per-Request/Response and vanishes the
        # same way when FEATURE_SHARDING is negotiated off.
        from horovod_tpu.common.message import Request, RequestType
        req = Request(request_type=RequestType.ALLREDUCE,
                      tensor_name="w", sp_spec="(tp,*)")
        rl2 = RequestList(requests=[req])
        back = RequestList.from_bytes(rl2.to_bytes(), wire.FEATURES_ALL)
        assert back.requests[0].sp_spec == "(tp,*)"
        base2 = RequestList.from_bytes(
            rl2.to_bytes(wire.PROTO_FEATURE_SETS[2]),
            wire.PROTO_FEATURE_SETS[2])
        assert base2.requests[0].sp_spec == ""

    def test_proto_compat_knob_masks_advertisement(self, monkeypatch):
        from horovod_tpu.runner.network import advertised_hello
        assert advertised_hello() == (wire.PROTO_VERSION,
                                      wire.FEATURES_ALL)
        monkeypatch.setenv("HOROVOD_PROTO_COMPAT", "1")
        assert advertised_hello() == (1, 0)


def _encode_response(resp, features):
    from horovod_tpu.common.wire import Encoder
    enc = Encoder()
    resp.encode(enc, features)
    return enc.getvalue()


# --- mixed-version world (mp battery) ---------------------------------------
def test_rolling_upgrade_mixed_proto_2rank():
    """ISSUE 15 rolling-upgrade battery: rank 1 speaks proto 1 (old
    framework); the world negotiates the min common schema, completes
    steps under strict fingerprinting with zero divergence, then the
    lagging rank upgrades and the world rejoins at the native proto."""
    outputs = _run_world(2, "rolling", timeout=180.0)
    assert all("ROLLING_OK" in out for out in outputs), outputs


# --- the full 4-rank acceptance battery -------------------------------------
def test_coordkill_then_shrink_grow_4rank(tmp_path):
    """ISSUE 15 acceptance: SIGKILL the rendezvous primary mid-run with
    heartbeats + statesync watchers live -> the standby promotes and
    clients fail over; a subsequent chaos SIGKILL of rank 2 rides the
    full 4->3->4 shrink/grow cycle — joiner bootstrap, donations and
    heartbeat table all served by the PROMOTED standby — with zero
    failed post-shrink steps; afterwards the live KV digest equals a
    fresh WAL replay (no committed write lost)."""
    ports = [free_port(), free_port()]
    eps = [f"127.0.0.1:{p}" for p in ports]
    proc = _spawn_primary_subprocess(tmp_path, eps, lease_ms=500.0)
    standby = RendezvousServer(port=ports[1], wal_dir=str(tmp_path),
                               replica_id=1, endpoints=eps,
                               lease_ms=500.0, standby=True)
    standby.start()
    try:
        # Launch rank 0's chaos engine SIGKILLs the rendezvous primary
        # at global collective 5 (deterministically mid-run, steps +
        # watchers + heartbeats live); the rank-2 SIGKILL at collective
        # 13 then rides the full shrink/grow against the PROMOTED
        # standby.
        outputs = _run_world(
            4, "statesync_grow", timeout=300.0,
            expected_rcs={2: -signal.SIGKILL},
            extra_env={
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": ",".join(eps),
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(ports[0]),
                "HOROVOD_RENDEZVOUS_EPOCH": "coordfail4",
                "HOROVOD_CHAOS": "coordkill:at=5;"
                                 "kill:rank=2,op=13,sig=9",
            })
        assert any("rode 4->3->4" in out for out in outputs), outputs
        assert any("SIGKILL rendezvous primary" in out
                   for out in outputs), outputs
        proc.wait(timeout=10)
        assert proc.returncode == -signal.SIGKILL
        assert standby.controlplane.role == "primary"
        assert standby.controlplane.failovers == 1
        # Quiescent after every worker exited: no committed write lost.
        assert standby.kv_digest() == cp.replay_state(
            cp.wal_path(str(tmp_path)))["digest"]
    finally:
        if proc.poll() is None:
            proc.kill()
        standby.stop()
