"""Callbacks, checkpointing, and the Trainer.fit loop
(reference surface: horovod/keras/callbacks.py, horovod/_keras/elastic.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import callbacks as cb
from horovod_tpu import checkpoint, training
from horovod_tpu.models.transformer import TransformerLM, gpt_tiny
from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh


class _FakeOpt:
    def __init__(self, lr):
        self.lr = lr


class TestLearningRateCallbacks:
    def test_schedule_staircase(self):
        opt = _FakeOpt(0.1)
        sched = cb.LearningRateScheduleCallback(
            opt, multiplier=lambda e: 0.1 ** e, start_epoch=0)
        sched.on_epoch_begin(0)
        assert opt.lr == pytest.approx(0.1)
        sched.on_epoch_begin(2)
        assert opt.lr == pytest.approx(0.1 * 0.01)

    def test_schedule_respects_range(self):
        opt = _FakeOpt(0.1)
        sched = cb.LearningRateScheduleCallback(
            opt, multiplier=2.0, start_epoch=2, end_epoch=4)
        sched.on_epoch_begin(0)
        assert opt.lr == pytest.approx(0.1)      # before start: untouched
        sched.on_epoch_begin(3)
        assert opt.lr == pytest.approx(0.2)
        sched.on_epoch_begin(5)
        assert opt.lr == pytest.approx(0.2)      # after end: frozen

    def test_warmup_ramps_to_configured_lr(self):
        # Reference convention (_keras/callbacks.py): the configured LR is
        # already size-scaled; warmup interpolates lr/size -> lr.
        opt = _FakeOpt(0.8)
        warm = cb.LearningRateWarmupCallback(opt, warmup_epochs=5,
                                             steps_per_epoch=10, size=8)
        warm.on_epoch_begin(0)
        warm.on_batch_begin(0)
        assert opt.lr == pytest.approx(0.1)      # start: lr / size
        warm.current_epoch = 4
        warm.on_batch_begin(9)
        # end of warmup: the configured (size-scaled) lr
        assert opt.lr == pytest.approx(0.8, rel=0.05)

    def test_torch_param_groups(self):
        torch = pytest.importorskip("torch")
        model = torch.nn.Linear(4, 4)
        opt = torch.optim.SGD(model.parameters(), lr=0.5)
        sched = cb.LearningRateScheduleCallback(opt, multiplier=0.1)
        sched.on_epoch_begin(0)
        assert opt.param_groups[0]["lr"] == pytest.approx(0.05)


class TestMetricAverage:
    def test_single_process_noop(self):
        import horovod_tpu as hvd
        hvd.init()
        try:
            logs = {"loss": 1.5, "name": "x"}
            cb.MetricAverageCallback().on_epoch_end(0, logs)
            assert logs["loss"] == 1.5
        finally:
            hvd.shutdown()


class TestFitLoop:
    def _setup(self):
        mesh = build_mesh(MeshSpec(dp=8))
        model = TransformerLM(gpt_tiny(dtype=jnp.float32))
        trainer = training.Trainer(
            model, optax.adamw(1e-3), mesh,
            sync=GradSyncConfig(axes=("dp",), op="average"))
        batch = training.synthetic_text_batch(8, seq_len=16, vocab_size=256)
        state = trainer.init(jax.random.key(0), batch)
        return trainer, state, batch

    def test_fit_runs_callbacks_and_improves(self):
        trainer, state, batch = self._setup()
        events = []

        class Recorder(cb.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                events.append(("eb", epoch))

            def on_epoch_end(self, epoch, logs=None):
                events.append(("ee", epoch, logs["loss"]))

            def on_batch_end(self, batch_i, logs=None):
                events.append(("b", batch_i))

        state, history = trainer.fit(state, [batch, batch], epochs=2,
                                     callbacks=[Recorder()])
        assert len(history) == 2
        assert history[1]["loss"] < history[0]["loss"]
        assert ("eb", 0) in events and ("eb", 1) in events
        assert sum(1 for e in events if e[0] == "b") == 4

    def test_best_model_checkpoint(self, tmp_path):
        trainer, state, batch = self._setup()
        saved = []
        best = cb.BestModelCheckpoint(
            str(tmp_path / "ckpt-{epoch}"), monitor="loss",
            save_fn=lambda path, st: saved.append(path))
        state, history = trainer.fit(state, [batch], epochs=2,
                                     callbacks=[best])
        # Loss improves each epoch → both saved.
        assert len(saved) == 2


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        tree = {"w": jnp.arange(12.0).reshape(3, 4),
                "step": jnp.int32(7)}
        path = str(tmp_path / "ck")
        checkpoint.save_checkpoint(path, tree)
        restored = checkpoint.restore_checkpoint(path)
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.asarray(tree["w"]))
        assert int(restored["step"]) == 7

    def test_latest_checkpoint(self, tmp_path):
        import os
        import time
        a, b = tmp_path / "1", tmp_path / "2"
        a.mkdir()
        time.sleep(0.01)
        b.mkdir()
        os.utime(b)
        assert checkpoint.latest_checkpoint(str(tmp_path)).endswith("2")
        assert checkpoint.latest_checkpoint(str(tmp_path / "nope")) is None


def test_warmup_adjusts_without_steps_per_epoch():
    """Regression: warmup must never silently no-op when steps_per_epoch
    is unknown — it falls back to epoch-granular adjustment."""
    from horovod_tpu import callbacks as cb

    class _Opt:
        lr = 0.1

    opt = _Opt()
    warm = cb.LearningRateWarmupCallback(opt, warmup_epochs=4, size=8)
    warm.on_epoch_begin(2)
    # halfway through warmup: (1 + (2/4)*(8-1)) / 8 = 0.5625x
    assert opt.lr == pytest.approx(0.1 * 0.5625)
    warm.on_epoch_begin(4)
    warm.on_epoch_begin(10)   # past warmup end: frozen at last value
    assert opt.lr == pytest.approx(0.1)
