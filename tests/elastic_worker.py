"""Worker script for the end-to-end elastic integration test.

The analogue of the reference's test/integration elastic training scripts:
train a counter via hvd.elastic.run with commits every step; a designated
"host" (localhost alias) hard-exits mid-training to simulate a node failure,
and the survivors must restore committed state, re-rendezvous at a smaller
world size, and finish.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

XLA_WORLD = bool(os.environ.get("TEST_ELASTIC_XLA"))
if XLA_WORLD:
    # Elastic x XLA: form a multi-process JAX world each epoch (VERDICT r2
    # item 5). Pin the CPU backend BEFORE anything touches jax — the axon
    # sitecustomize may already have imported it.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["HOROVOD_JAX_DISTRIBUTED"] = "1"
    os.environ["HOROVOD_XLA_OPERATIONS"] = "1"
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import horovod_tpu as hvd
from horovod_tpu.elastic import ObjectState
from horovod_tpu.elastic.run import run as elastic_run

FAIL_HOST = os.environ.get("TEST_ELASTIC_FAIL_HOST", "")
FAIL_EPOCH = int(os.environ.get("TEST_ELASTIC_FAIL_EPOCH", "2"))
TARGET = int(os.environ.get("TEST_ELASTIC_TARGET", "5"))
OUT_DIR = os.environ["TEST_ELASTIC_OUT"]


@elastic_run
def train(state):
    while state.epoch < TARGET:
        hostname = os.environ.get("HOROVOD_HOSTNAME", "")
        if hostname == FAIL_HOST and state.epoch == FAIL_EPOCH:
            os._exit(17)   # simulate sudden node death
        # Cross-rank step: every live rank must agree on the result.
        out = hvd.allreduce(np.ones(4, np.float32) * (state.epoch + 1),
                            average=False, name=f"step")
        expected = (state.epoch + 1) * hvd.size()
        np.testing.assert_allclose(np.asarray(out), np.full(4, expected),
                                   rtol=1e-6)
        if XLA_WORLD and hvd.size() > 1:
            # The collective must have ridden the freshly (re-)formed XLA
            # device plane, not fallen back to the TCP ring.
            from horovod_tpu.core import _global
            backend = _global.op_manager.backends[0]
            assert backend.name == "xla", backend.name
            assert backend.comm._cache, "xla plane never executed"
        state.epoch += 1
        state.commit()
    return state.epoch


def main() -> int:
    state = ObjectState(epoch=0)
    result = train(state)
    if result is None:
        return 0   # dropped from the world: clean exit
    marker = os.path.join(
        OUT_DIR, f"done.{os.environ.get('HOROVOD_HOSTNAME')}."
                 f"{os.environ.get('HOROVOD_LOCAL_RANK')}")
    with open(marker, "w") as f:
        f.write(f"{result} {hvd.size()} {hvd.rank()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
