"""KV-block pool unit battery (ISSUE 14): free-list allocation with
refcounts, FNV chain-hash prefix caching with collision safety,
copy-on-write semantics and LRU eviction — the id-bookkeeping half of
the paged serving plane (the tensor half is parity-tested in
tests/test_transformer.py and end-to-end in test_serving.py)."""
from __future__ import annotations

import pytest

from horovod_tpu.serving.kvpool import FNV_SEED, KVBlockPool, chain_hash
from horovod_tpu.telemetry.registry import MetricsRegistry


def _pool(blocks=8, bt=4):
    return KVBlockPool(blocks, bt, registry=MetricsRegistry(0))


# --- allocation / refcounts --------------------------------------------------
def test_alloc_refcount_and_free_list_reuse():
    p = _pool(4)
    a = p.alloc(2)
    assert sorted(a) == [0, 1] and p.free_count() == 2
    assert p.active_count() == 2
    p.ref(a[0])
    assert p.refcount(a[0]) == 2
    p.deref(a[0])
    assert p.refcount(a[0]) == 1 and p.active_count() == 2
    p.deref(a[0])
    # Unpublished block at refcount 0 frees immediately and is reused.
    assert p.free_count() == 3
    b = p.alloc(3)
    assert a[0] in b                      # free-list reuse
    p.release_all()
    assert p.free_count() == 4 and p.active_count() == 0


def test_alloc_exhaustion_is_backpressure_not_error():
    p = _pool(3)
    assert p.alloc(4) is None             # over capacity: defer
    got = p.alloc(3)
    assert len(got) == 3
    assert p.alloc(1) is None
    p.deref(got[0])
    assert p.alloc(1) == [got[0]]


def test_ref_of_unowned_block_raises():
    p = _pool(2)
    with pytest.raises(ValueError):
        p.ref(0)
    with pytest.raises(ValueError):
        p.deref(1)


# --- prefix cache ------------------------------------------------------------
def test_publish_lookup_chain_and_lru_park():
    p = _pool(8, bt=4)
    blocks = p.alloc(2)
    k0 = p.publish(blocks[0], FNV_SEED, [1, 2, 3, 4])
    p.publish(blocks[1], k0, [5, 6])      # partial tail, count-keyed
    # Another sequence with the same prefix hits both links.
    h0 = p.lookup(FNV_SEED, [1, 2, 3, 4])
    assert h0 == blocks[0] and p.refcount(blocks[0]) == 2
    h1 = p.lookup(k0, [5, 6])
    assert h1 == blocks[1]
    # Different tail tokens: miss (the chain key differs).
    assert p.lookup(k0, [5, 7]) is None
    assert p.lookup(k0, [5, 6, 7]) is None
    # Deref to zero parks published blocks on the LRU, still hittable.
    for b in blocks:
        p.deref(b)
        p.deref(b)
    assert p.active_count() == 0 and p.cached_count() == 2
    assert p.lookup(FNV_SEED, [1, 2, 3, 4]) == blocks[0]
    assert p.active_count() == 1          # revived off the LRU


def test_hash_collision_is_a_miss_not_corruption(monkeypatch):
    p = _pool(4)
    blocks = p.alloc(2)
    # Force both publishes onto one chain key: the second keeps the
    # incumbent mapping, and a lookup whose token ids differ from the
    # stored ones must MISS instead of returning wrong-content blocks.
    monkeypatch.setattr("horovod_tpu.serving.kvpool.chain_hash",
                        lambda parent, tokens: 42)
    p.publish(blocks[0], FNV_SEED, [1, 2])
    p.publish(blocks[1], FNV_SEED, [3, 4])   # colliding key: kept out
    assert p.lookup(FNV_SEED, [1, 2]) == blocks[0]
    p.deref(blocks[0])
    assert p.lookup(FNV_SEED, [3, 4]) is None
    assert p.lookup(FNV_SEED, [9, 9]) is None


def test_chain_hash_orders_and_links():
    assert chain_hash(FNV_SEED, [1, 2]) != chain_hash(FNV_SEED, [2, 1])
    k1 = chain_hash(FNV_SEED, [1, 2])
    assert chain_hash(k1, [3]) != chain_hash(FNV_SEED, [3])


# --- LRU eviction ------------------------------------------------------------
def test_lru_eviction_oldest_first_under_pressure():
    p = _pool(4, bt=4)
    blocks = p.alloc(4)
    keys = [FNV_SEED]
    for i, b in enumerate(blocks):
        keys.append(p.publish(b, keys[-1], [i]))
        p.deref(b)
    assert p.cached_count() == 4 and p.free_count() == 0
    # Touch block 0 (a hit) so it becomes most-recently-used.
    assert p.lookup(FNV_SEED, [0]) == blocks[0]
    p.deref(blocks[0])
    # Allocation under pressure evicts the LRU tail: blocks 1 then 2.
    fresh = p.alloc(2)
    assert fresh == [blocks[1], blocks[2]]
    assert p._m_evicted.value == 2
    # Block 0 survived (recently used); block 1's mapping is gone.
    assert p.lookup(FNV_SEED, [0]) == blocks[0]
    assert p.lookup(keys[1], [1]) is None


# --- copy-on-write -----------------------------------------------------------
def test_cow_private_block_is_noop():
    p = _pool(4)
    b = p.alloc(1)[0]
    assert p.cow(b) == (b, False)


def test_cow_on_shared_and_published_blocks():
    p = _pool(4)
    b = p.alloc(1)[0]
    p.ref(b)                              # two holders
    assert p.is_shared(b)
    nb, copied = p.cow(b)
    assert copied and nb != b
    assert p.refcount(b) == 1 and p.refcount(nb) == 1
    # Published ⇒ immutable even at refcount 1: the hash certifies the
    # contents, so extending the tail must copy first.
    p.publish(b, FNV_SEED, [7, 8])
    nb2, copied2 = p.cow(b)
    assert copied2 and nb2 not in (b,)
    # The published original parked on the LRU, still a valid hit.
    assert p.lookup(FNV_SEED, [7, 8]) == b


def test_cow_exhaustion_names_the_headroom_contract():
    p = _pool(1)
    b = p.alloc(1)[0]
    p.ref(b)
    with pytest.raises(RuntimeError, match="headroom"):
        p.cow(b)


# --- telemetry + teardown ----------------------------------------------------
def test_gauges_and_counters_track_states():
    reg = MetricsRegistry(0)
    p = KVBlockPool(4, 4, registry=reg)

    def gauge(state):
        return reg.gauge("horovod_serve_kv_blocks",
                         labels={"state": state}).value

    blocks = p.alloc(2)
    assert (gauge("free"), gauge("active"), gauge("cached")) == (2, 2, 0)
    p.publish(blocks[0], FNV_SEED, [1])
    p.deref(blocks[0])
    assert (gauge("free"), gauge("active"), gauge("cached")) == (2, 1, 1)
    p.lookup(FNV_SEED, [1])
    p.lookup(FNV_SEED, [2])
    assert reg.counter("horovod_serve_prefix_hits_total").value == 1
    assert reg.counter("horovod_serve_prefix_misses_total").value == 1
    p.close()
    assert (gauge("free"), gauge("active"), gauge("cached")) == (4, 0, 0)


def test_close_is_idempotent_and_releases_everything():
    p = _pool(4)
    p.alloc(3)
    p.close()
    p.close()
    assert p.free_count() == 4 and p.active_count() == 0
