"""hvdshard tests (analysis/hvdshard/): the canonical spec-token
grammar, the shared rule-coverage core, HVD801-804 on the seeded
fixtures, the CLI, and the lint --shard driver integration.  The
runtime half of op×name×dtype×dims×spec identity (fingerprint fold,
sp_* wire fields) is covered in test_fingerprint.py /
test_controlplane.py; the 2-rank acceptance battery lives in
tests/test_multiprocess.py."""
import json
import os
import subprocess
import sys

from horovod_tpu.analysis.hvdshard import (fold_token, missing_axes,
                                           rule_coverage, spec_token,
                                           token_axes)
from horovod_tpu.analysis.hvdshard.shard import (SHARD_RULE_IDS,
                                                 analyze_paths)
from horovod_tpu.analysis.hvdshard.shard import main as shard_main
from horovod_tpu.analysis.lint import LintConfig, lint_paths_timed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHARD = os.path.join(REPO, "tests", "fixtures", "lint", "shard")


def _fx(name: str) -> str:
    return os.path.join(SHARD, name)


def _rules(findings):
    return [f.rule.id for f in findings]


# --- the canonical token grammar ---------------------------------------------
def test_spec_token_grammar():
    assert spec_token(None) == ""
    assert spec_token(()) == "*"                      # P() replicated
    assert spec_token(("tp",)) == "(tp)"
    assert spec_token((None, "tp")) == "(*,tp)"
    assert spec_token((("dp", "fsdp"), None)) == "(dp+fsdp,*)"
    assert spec_token("(tp,*)") == "(tp,*)"           # idempotent


def test_fold_token_wildcards_allgather_dim0():
    # ALLGATHER's first dim is rank-local by contract (uneven rows):
    # folding its spec entry would flag every legitimate uneven gather.
    assert fold_token("ALLGATHER", "(dp,tp)") == "(*,tp)"
    assert fold_token("ALLREDUCE", "(dp,tp)") == "(dp,tp)"
    assert fold_token("ALLGATHER", "*") == "*"
    assert fold_token("ALLGATHER", "") == ""


def test_token_axes_and_missing_axes():
    assert token_axes("(dp+fsdp,*)") == {"dp", "fsdp"}
    assert token_axes("*") == set()
    assert token_axes("") == set()
    assert missing_axes("(model,*)", ("dp", "tp")) == ["model"]
    assert missing_axes("(dp,tp)", ("dp", "tp")) == []


def test_rule_coverage_dead_and_uncovered():
    table = [("decoder/.*", "(*,tp)"), ("attn/wq", "(*,tp)")]
    paths = ["attn/wq", "attn/wk"]
    dead, uncovered = rule_coverage(table, paths)
    assert dead == ["decoder/.*"]
    assert uncovered == [("attn/wk", "attn/wq")]


def test_rule_coverage_replicated_sibling_is_not_sharded():
    # A sibling matched by an explicitly-replicated rule ('*') does not
    # make an unmatched neighbour "uncovered".
    table = [("attn/wq", "*")]
    dead, uncovered = rule_coverage(table, ["attn/wq", "attn/wk"])
    assert dead == [] and uncovered == []


# --- seeded fixtures: flagged/clean pairs ------------------------------------
def test_fixture_dead_rule_flagged_and_clean():
    out = analyze_paths([_fx("dead_rule.py")])
    assert _rules(out) == ["HVD801"] * 2
    msgs = " | ".join(f.message for f in out)
    assert "decoder/.*kernel" in msgs                 # dead rule named
    assert "attn/wk" in msgs and "attn/wq" in msgs    # path + sibling rule
    assert all(f.severity == "warning" for f in out)
    assert analyze_paths([_fx("dead_rule_clean.py")]) == []


def test_fixture_axis_mismatch_flagged_and_clean():
    out = analyze_paths([_fx("axis_mismatch.py")])
    assert _rules(out) == ["HVD802"]
    assert out[0].severity == "error"
    assert "'model'" in out[0].message
    assert "['dp', 'tp']" in out[0].message
    assert analyze_paths([_fx("axis_mismatch_clean.py")]) == []


def test_fixture_divergent_spec_flagged_and_clean():
    out = analyze_paths([_fx("divergent_spec.py")])
    assert _rules(out) == ["HVD803"]
    f = out[0]
    assert f.severity == "error"
    assert "allreduce(grads/w|(tp,*))" in f.message
    assert "allreduce(grads/w|(dp,*))" in f.message
    assert "first spec-divergent op #1" in f.message
    assert analyze_paths([_fx("divergent_spec_clean.py")]) == []


def test_fixture_spec_drop_flagged_and_clean():
    out = analyze_paths([_fx("spec_drop.py")])
    assert _rules(out) == ["HVD804"] * 3
    producers = {f.message.split("assigned from ")[1].split("(")[0]
                 for f in out}
    assert producers == {"shard_params", "constrain", "device_put"}
    assert all(f.severity == "warning" for f in out)
    assert analyze_paths([_fx("spec_drop_clean.py")]) == []


def test_all_shard_fixtures_flagged_together():
    out = analyze_paths([SHARD])
    assert sorted(set(_rules(out))) == ["HVD801", "HVD802", "HVD803",
                                        "HVD804"]


# --- CLI ---------------------------------------------------------------------
def test_cli_json(capsys):
    rc = shard_main([_fx("axis_mismatch.py"), "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["shard"]] == ["HVD802"]
    assert payload["wall_ms"] > 0


def test_cli_warnings_exit_zero(capsys):
    rc = shard_main([_fx("spec_drop.py"), "--format", "json"])
    capsys.readouterr()
    assert rc == 0          # warnings only: the gate is on errors


def test_cli_sarif(capsys):
    rc = shard_main([_fx("divergent_spec.py"), "--format", "sarif"])
    assert rc == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["HVD803"]
    assert results[0]["level"] == "error"


def test_cli_module_entrypoint():
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.hvdshard",
         _fx("dead_rule.py"), "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert [f["rule"] for f in payload["shard"]] == ["HVD801"] * 2


def test_lint_driver_shard_rides_same_parse():
    """`lint --shard` runs hvdshard (and the HVD803 leg of hvdflow)
    over the same single parse; findings respect --select/--ignore."""
    cfg = LintConfig()
    _v, findings, stats = lint_paths_timed(
        [_fx("divergent_spec.py")], cfg, shard=True)
    assert _rules(findings) == ["HVD803"]
    assert stats["files"] == 1
    cfg = LintConfig(ignore={"HVD803"})
    _v, findings, _s = lint_paths_timed(
        [_fx("divergent_spec.py")], cfg, shard=True)
    assert findings == []
    # Without --shard the same parse yields no HVD80x: the partition.
    _v, findings, _s = lint_paths_timed(
        [_fx("divergent_spec.py")], LintConfig(), flow=True)
    assert findings == []


def test_shard_rule_ids_registered():
    from horovod_tpu.analysis.rules import RULES
    assert SHARD_RULE_IDS == {"HVD801", "HVD802", "HVD803", "HVD804"}
    for rid in SHARD_RULE_IDS:
        assert rid in RULES
    assert RULES["HVD801"].slug == "dead-partition-rule"
    assert RULES["HVD802"].slug == "spec-mesh-axis-mismatch"
    assert RULES["HVD803"].slug == "divergent-spec-collective"
    assert RULES["HVD804"].slug == "spec-drop"


def test_suppression_silences_shard_finding(tmp_path):
    src = open(_fx("axis_mismatch.py"), encoding="utf-8").read()
    src = src.replace(
        'return constrain(x, mesh, P("model", None))',
        'return constrain(x, mesh, P("model", None))'
        '  # hvdlint: disable=HVD802 -- megatron import shim')
    p = tmp_path / "suppressed.py"
    p.write_text(src)
    assert analyze_paths([str(p)]) == []
