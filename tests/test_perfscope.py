"""perfscope end-to-end battery (ISSUE 19 acceptance): the 2-rank
metrics-on world produces busbw cells the perf CLI merges into one
PERF.json, perfcheck gates that ledger against itself (pass) and against
a doctored -30% busbw twin (structured failure naming the cell), the
4-rank synthetic merge covers ring/tree/rhd at three size buckets, and
the Trainer reports a nonzero MFU for a TransformerLM step on CPU."""
from __future__ import annotations

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.telemetry import perf, perfcheck, perfmodel
from horovod_tpu.telemetry.registry import MetricsRegistry

from test_multiprocess import _run_world


def _synthetic_dumps(tmp_path, ranks=4):
    """Rank metric dumps with busbw cells for ring/tree/rhd across the
    4KiB/64KiB/1MiB buckets — the shape a 4-rank algo-sweep run leaves
    behind, without needing a power-of-two live world in this test."""
    base = {"4KiB": 40.0, "64KiB": 160.0, "1MiB": 260.0}
    factor = {"ring": 1.0, "rhd": 0.95, "tree": 0.5}
    paths = []
    for r in range(ranks):
        reg = MetricsRegistry(r)
        for algo, f in factor.items():
            for bucket, busbw in base.items():
                h = reg.histogram(
                    "horovod_collective_busbw_mbps", "busbw",
                    labels={"plane": "tcp", "op": "allreduce",
                            "codec": "none", "algo": algo,
                            "size_bucket": bucket})
                for i in range(3):
                    h.observe(busbw * f * (1.0 + 0.01 * ((r + i) % 3)))
        path = tmp_path / f"dump.r{r}.json"
        path.write_text(json.dumps(reg.snapshot()))
        paths.append(str(path))
    return paths


def test_perf_cli_merges_4rank_synthetic_algo_sweep(tmp_path, capsys):
    """Acceptance: the CLI merges 4 rank dumps into one PERF.json whose
    busbw table covers ring/tree/rhd at >= 3 size buckets with
    roofline-relative efficiency."""
    paths = _synthetic_dumps(tmp_path)
    out = tmp_path / "PERF.json"
    rc = perf.main(paths + ["-o", str(out), "--size", "4",
                            "--topology", "torus:2x2"])
    assert rc == 0
    ledger = json.loads(out.read_text())
    assert ledger["schema"] == 1
    assert ledger["world"] == {"ranks": 4, "dumps": 4,
                               "topology": "torus:2x2"}
    rows = ledger["busbw"]
    for algo in ("ring", "tree", "rhd"):
        buckets = {r["size_bucket"] for r in rows if r["algo"] == algo}
        assert {"4KiB", "64KiB", "1MiB"} <= buckets, (algo, buckets)
    assert ledger["peak_source"] == "self-calibrated"
    assert ledger["peak_mbps"] == pytest.approx(
        max(r["busbw_mbps"] for r in rows))
    for r in rows:
        assert 0.0 < r["efficiency"] <= 1.05, r
        assert r["roofline_mbps"] > 0.0
        assert r["algo_overhead"] >= 1.0
    # The tree runs at half the ring's busbw in the synthetic data; the
    # efficiency column must show that gap, not normalize it away.
    ring_1m = next(r for r in rows
                   if r["algo"] == "ring" and r["size_bucket"] == "1MiB")
    tree_1m = next(r for r in rows
                   if r["algo"] == "tree" and r["size_bucket"] == "1MiB")
    assert tree_1m["efficiency"] < 0.6 * ring_1m["efficiency"]


def test_perfcheck_catches_seeded_regression(tmp_path, capsys):
    """Acceptance: perfcheck passes a ledger against itself and fails a
    doctored -30% busbw current with a structured finding naming the
    (plane, algo, size-bucket) cell."""
    paths = _synthetic_dumps(tmp_path)
    out = tmp_path / "PERF.json"
    assert perf.main(paths + ["-o", str(out), "--size", "4"]) == 0
    # Self-comparison: identical cells, no findings, exit 0.
    assert perfcheck.main([str(out), "--baseline", str(out)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["findings"] == []

    doctored = json.loads(out.read_text())
    for row in doctored["busbw"]:
        row["busbw_mbps"] *= 0.7
    bad = tmp_path / "PERF.regressed.json"
    bad.write_text(json.dumps(doctored))
    rc = perfcheck.main([str(bad), "--baseline", str(out),
                         "--tolerance-pct", "10"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "REGRESSION" in captured.err
    report = json.loads(captured.out)
    assert report["findings"], captured.out
    for f in report["findings"]:
        assert f["metric"] == "busbw_mbps"
        assert f["plane"] == "tcp"
        assert f["size_bucket"] in ("4KiB", "64KiB", "1MiB")
        assert f["algo"] in ("ring", "tree", "rhd")
        assert f["delta_pct"] == pytest.approx(-30.0, abs=0.2)


def test_perfscope_2rank_world(tmp_path, capsys):
    """ISSUE 19 tier-1 smoke: a real 2-rank metrics-on world (in-battery
    assertions: ledger produced, efficiency in (0, 1.05], known algos)
    whose shutdown dumps merge through the perf CLI and pass perfcheck
    against their own ledger; a doctored -30% baseline window fails."""
    for stale in glob.glob("/tmp/hvd_perf_perfscope2.r*.json"):
        os.unlink(stale)
    _run_world(2, "perfscope", timeout=240.0)
    dumps = [f"/tmp/hvd_perf_perfscope2.r{r}.json" for r in range(2)]
    for d in dumps:
        assert os.path.exists(d), f"rank dump missing: {d}"
    out = tmp_path / "PERF.json"
    assert perf.main(dumps + ["-o", str(out), "--size", "2"]) == 0
    ledger = json.loads(out.read_text())
    rows = ledger["busbw"]
    assert rows, "2-rank world produced no busbw cells"
    assert ledger["world"]["dumps"] == 2
    assert {"4KiB", "64KiB", "1MiB"} <= {r["size_bucket"] for r in rows}
    for r in rows:
        assert 0.0 < r["efficiency"] <= 1.05, r
        assert r["algo"] == "ring", r   # 2 ranks: every schedule degenerates
    # Gate against itself: clean.
    assert perfcheck.main([str(out), "--baseline", str(out)]) == 0
    capsys.readouterr()
    # Doctor the CURRENT ledger 30% down; the gate must name a cell.
    doctored = json.loads(out.read_text())
    for row in doctored["busbw"]:
        row["busbw_mbps"] *= 0.7
    bad = tmp_path / "PERF.regressed.json"
    bad.write_text(json.dumps(doctored))
    rc = perfcheck.main([str(bad), "--baseline", str(out),
                         "--tolerance-pct", "10"])
    captured = capsys.readouterr()
    assert rc == 1
    finding = json.loads(captured.out)["findings"][0]
    assert finding["plane"] == "tcp"
    assert finding["algo"] == "ring"
    assert finding["size_bucket"] in ("4KiB", "64KiB", "1MiB")


def test_trainer_reports_nonzero_mfu_for_transformer(monkeypatch):
    """Acceptance: the Trainer reports a nonzero MFU for a TransformerLM
    step — on CPU the nominal 1 TFLOP/chip peak keeps the ratio small
    but strictly positive.  MFU needs two steps: the first dispatch only
    arms the inter-dispatch clock."""
    from horovod_tpu import telemetry, training
    from horovod_tpu.models.transformer import TransformerLM, gpt_tiny
    from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh

    monkeypatch.setenv("HOROVOD_METRICS", "on")
    reg = telemetry.configure()
    try:
        mesh = build_mesh(MeshSpec(dp=8))
        model = TransformerLM(gpt_tiny(dtype=jnp.float32))
        trainer = training.Trainer(
            model, optax.adamw(1e-3), mesh,
            sync=GradSyncConfig(axes=("dp",), op="average"))
        batch = training.synthetic_text_batch(8, seq_len=16,
                                              vocab_size=256)
        state = trainer.init(jax.random.key(0), batch)
        state, _ = trainer.step(state, batch)
        state, metrics = trainer.step(state, batch)
        jax.block_until_ready(metrics)
        flops = reg.gauge("horovod_train_step_flops").value
        mfu = reg.gauge("horovod_train_mfu").value
        assert flops > 0.0
        assert 0.0 < mfu < 1.0, mfu
        # The analytic FLOPs match the model card: 6 * params-ish for
        # the tiny config, sanity-bounded rather than pinned.
        card = perfmodel.transformer_train_flops(
            model.cfg, 8, 16)
        assert flops == pytest.approx(card)
        snap = {m["name"]: m for m in reg.snapshot()["metrics"]}
        assert snap["horovod_train_step_ms"]["count"] >= 1
    finally:
        monkeypatch.delenv("HOROVOD_METRICS", raising=False)
        telemetry.configure()


def test_summary_stamps_perf_ledger(monkeypatch):
    """bench payload stamp: telemetry.summary() carries the perf ledger
    whenever busbw or step evidence exists in the registry."""
    from horovod_tpu import telemetry

    monkeypatch.setenv("HOROVOD_METRICS", "on")
    reg = telemetry.configure()
    try:
        reg.histogram(
            "horovod_collective_busbw_mbps", "busbw",
            labels={"plane": "tcp", "op": "allreduce", "codec": "none",
                    "algo": "ring", "size_bucket": "64KiB"}).observe(120.0)
        out = telemetry.summary()
        assert "perf" in out
        assert out["perf"]["busbw"][0]["algo"] == "ring"
        assert out["perf"]["busbw"][0]["efficiency"] == pytest.approx(1.0)
    finally:
        monkeypatch.delenv("HOROVOD_METRICS", raising=False)
        telemetry.configure()
