"""ShmWorld unit tests: formation, lockstep, and the poison protocol
(fallible I/O between barrier publishes — e.g. the hierarchical cross
leg — must fail every rank fast, not hang peers until the barrier
timeout or complete with partial reductions)."""
from __future__ import annotations

import threading

import numpy as np
import pytest

from horovod_tpu.backend.shm import ShmWorld, _POISON
from horovod_tpu.runner.network import RendezvousClient, RendezvousServer


@pytest.fixture()
def kv():
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 10.0)
    server.stop()


def _form_world(kv, scope: str, n: int = 2, capacity: int = 1 << 16):
    """Form an n-rank world with all ranks in one process (instances
    attaching to each other's regions — formation needs concurrency)."""
    worlds: list = [None] * n
    errors: list = []

    def make(rank: int) -> None:
        try:
            worlds[rank] = ShmWorld(rank, n, kv, scope=scope,
                                    capacity=capacity, timeout=10.0)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=make, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20.0)
    assert not errors, errors
    assert all(w is not None and w.formed for w in worlds), worlds
    return worlds


def _form_pair(kv, scope: str, capacity: int = 1 << 16):
    return _form_world(kv, scope, 2, capacity)


def test_shm_world_forms_and_steps(kv):
    a, b = _form_pair(kv, "unit1")
    try:
        a.data(0)[:4] = np.frombuffer(b"\x01\x02\x03\x04", np.uint8)
        # b reads a's region through its own mapping (shared memory).
        assert bytes(b.data(0)[:4]) == b"\x01\x02\x03\x04"
        a.publish(3)
        b.publish(3)
        a.wait_all(3)
        b.wait_all(3)
    finally:
        a.close()
        b.close()


def test_shm_poison_unblocks_waiters(kv):
    a, b = _form_pair(kv, "unit2")
    try:
        result: list = []

        def waiter():
            try:
                a.wait_all(5)
                result.append("returned")
            except ConnectionError:
                result.append("poisoned")

        th = threading.Thread(target=waiter)
        th.start()
        b.poison()
        th.join(10.0)
        assert not th.is_alive(), "waiter should have been unblocked"
        assert result == ["poisoned"]
        assert not b.formed
        assert not a.formed   # detection side also opts out of future ops
    finally:
        a.close()
        b.close()


def test_shm_poison_carries_high_water_mark(kv):
    """A rank that fails AFTER publishing seq k poisons to _POISON+k:
    barriers <= k (data already staged) still complete on peers; barriers
    beyond k raise.  This is the post-op-failure case — without the mark,
    a slow peer still draining op t's last wait would error an op whose
    data was fully published."""
    a, b = _form_pair(kv, "unit3")
    try:
        b.publish(4)        # b completed through seq 4...
        b.poison()          # ...then failed
        assert int(b._seqs[1][0]) == _POISON + 4
        a.publish(4)
        a.wait_all(4)       # satisfied by b's published progress: no raise
        with pytest.raises(ConnectionError):
            a.wait_all(5)   # beyond b's mark: will never arrive
        assert not a.formed
    finally:
        a.close()
        b.close()


def test_shm_poison_is_idempotent(kv):
    a, b = _form_pair(kv, "unit3b")
    try:
        b.publish(2)
        b.poison()
        b.poison()          # double-fault keeps the original mark
        assert int(b._seqs[1][0]) == _POISON + 2
    finally:
        a.close()
        b.close()


def test_shm_poison_mark_does_not_error_live_slow_rank(kv):
    """3-rank world: c completes through seq 2 then poisons; a is live
    but still at seq 1.  b's wait_all(2) must KEEP WAITING for a (live
    slow ranks are the liveness poll's job), not raise on c's covering
    mark — and must complete once a catches up.  Raising here would make
    the same collective fail on b but succeed on a (rank-divergent
    outcome)."""
    a, b, c = _form_world(kv, "unit3c", n=3)
    try:
        a.publish(1)
        b.publish(2)
        c.publish(2)
        c.poison()
        assert int(c._seqs[2][0]) == _POISON + 2

        result: list = []

        def waiter():
            try:
                b.wait_all(2)
                result.append("completed")
            except ConnectionError:
                result.append("poisoned")

        th = threading.Thread(target=waiter)
        th.start()
        th.join(0.5)
        assert th.is_alive(), "b must wait for live rank a, not raise"
        a.publish(2)          # slow rank catches up
        th.join(10.0)
        assert result == ["completed"]
        with pytest.raises(ConnectionError):
            b.wait_all(3)     # beyond c's mark: genuinely unsatisfiable
    finally:
        a.close()
        b.close()
        c.close()


def test_shm_poison_seen_declines_next_op(kv):
    """enabled()'s cross-rank probe: after any rank poisons, EVERY rank's
    poison_seen() is True before the next op is claimed — the unanimous
    TCP fallback that prevents a one-op plane desync."""
    a, b = _form_pair(kv, "unit4")
    try:
        assert not a.poison_seen() and not b.poison_seen()
        b.poison()
        assert a.poison_seen()      # peer sees the mark...
        assert not a.formed         # ...and opts out locally
        assert b.poison_seen()
    finally:
        a.close()
        b.close()
