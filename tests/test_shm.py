"""ShmWorld/ShmBackend unit tests: formation, lockstep, the poison
protocol (fallible I/O between barrier publishes — e.g. the hierarchical
cross leg — must fail every rank fast, not hang peers until the barrier
timeout or complete with partial reductions), and the per-op protocol
branches: alltoall sentinel flags, dead-peer liveness, poison during
each collective, fused multi-tensor responses."""
from __future__ import annotations

import os
import threading

import numpy as np
import pytest

from horovod_tpu.backend.shm import ShmBackend, ShmWorld, _POISON
from horovod_tpu.common.dtypes import from_any
from horovod_tpu.common.message import Response, ResponseType
from horovod_tpu.common.status import Status
from horovod_tpu.common.tensor_queue import TensorTableEntry
from horovod_tpu.runner.network import RendezvousClient, RendezvousServer


@pytest.fixture()
def kv():
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 10.0)
    server.stop()


def _form_world(kv, scope: str, n: int = 2, capacity: int = 1 << 16):
    """Form an n-rank world with all ranks in one process (instances
    attaching to each other's regions — formation needs concurrency)."""
    worlds: list = [None] * n
    errors: list = []

    def make(rank: int) -> None:
        try:
            worlds[rank] = ShmWorld(rank, n, kv, scope=scope,
                                    capacity=capacity, timeout=10.0)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=make, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20.0)
    assert not errors, errors
    assert all(w is not None and w.formed for w in worlds), worlds
    return worlds


def _form_pair(kv, scope: str, capacity: int = 1 << 16):
    return _form_world(kv, scope, 2, capacity)


def test_shm_world_forms_and_steps(kv):
    a, b = _form_pair(kv, "unit1")
    try:
        a.data(0)[:4] = np.frombuffer(b"\x01\x02\x03\x04", np.uint8)
        # b reads a's region through its own mapping (shared memory).
        assert bytes(b.data(0)[:4]) == b"\x01\x02\x03\x04"
        a.publish(3)
        b.publish(3)
        a.wait_all(3)
        b.wait_all(3)
    finally:
        a.close()
        b.close()


def test_shm_poison_unblocks_waiters(kv):
    a, b = _form_pair(kv, "unit2")
    try:
        result: list = []

        def waiter():
            try:
                a.wait_all(5)
                result.append("returned")
            except ConnectionError:
                result.append("poisoned")

        th = threading.Thread(target=waiter)
        th.start()
        b.poison()
        th.join(10.0)
        assert not th.is_alive(), "waiter should have been unblocked"
        assert result == ["poisoned"]
        assert not b.formed
        assert not a.formed   # detection side also opts out of future ops
    finally:
        a.close()
        b.close()


def test_shm_poison_carries_high_water_mark(kv):
    """A rank that fails AFTER publishing seq k poisons to _POISON+k:
    barriers <= k (data already staged) still complete on peers; barriers
    beyond k raise.  This is the post-op-failure case — without the mark,
    a slow peer still draining op t's last wait would error an op whose
    data was fully published."""
    a, b = _form_pair(kv, "unit3")
    try:
        b.publish(4)        # b completed through seq 4...
        b.poison()          # ...then failed
        assert int(b._seqs[1][0]) == _POISON + 4
        a.publish(4)
        a.wait_all(4)       # satisfied by b's published progress: no raise
        with pytest.raises(ConnectionError):
            a.wait_all(5)   # beyond b's mark: will never arrive
        assert not a.formed
    finally:
        a.close()
        b.close()


def test_shm_poison_is_idempotent(kv):
    a, b = _form_pair(kv, "unit3b")
    try:
        b.publish(2)
        b.poison()
        b.poison()          # double-fault keeps the original mark
        assert int(b._seqs[1][0]) == _POISON + 2
    finally:
        a.close()
        b.close()


def test_shm_poison_mark_does_not_error_live_slow_rank(kv):
    """3-rank world: c completes through seq 2 then poisons; a is live
    but still at seq 1.  b's wait_all(2) must KEEP WAITING for a (live
    slow ranks are the liveness poll's job), not raise on c's covering
    mark — and must complete once a catches up.  Raising here would make
    the same collective fail on b but succeed on a (rank-divergent
    outcome)."""
    a, b, c = _form_world(kv, "unit3c", n=3)
    try:
        a.publish(1)
        b.publish(2)
        c.publish(2)
        c.poison()
        assert int(c._seqs[2][0]) == _POISON + 2

        result: list = []

        def waiter():
            try:
                b.wait_all(2)
                result.append("completed")
            except ConnectionError:
                result.append("poisoned")

        th = threading.Thread(target=waiter)
        th.start()
        th.join(0.5)
        assert th.is_alive(), "b must wait for live rank a, not raise"
        a.publish(2)          # slow rank catches up
        th.join(10.0)
        assert result == ["completed"]
        with pytest.raises(ConnectionError):
            b.wait_all(3)     # beyond c's mark: genuinely unsatisfiable
    finally:
        a.close()
        b.close()
        c.close()


def test_shm_poison_seen_declines_next_op(kv):
    """enabled()'s cross-rank probe: after any rank poisons, EVERY rank's
    poison_seen() is True before the next op is claimed — the unanimous
    TCP fallback that prevents a one-op plane desync."""
    a, b = _form_pair(kv, "unit4")
    try:
        assert not a.poison_seen() and not b.poison_seen()
        b.poison()
        assert a.poison_seen()      # peer sees the mark...
        assert not a.formed         # ...and opts out locally
        assert b.poison_seen()
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# ShmBackend protocol-branch tests: two in-process "ranks" drive the real
# lockstep concurrently (threads), pinning each sentinel/failure branch.
# ---------------------------------------------------------------------------
import contextlib


@contextlib.contextmanager
def _backend_pair(kv, scope: str, capacity: int = 1 << 16):
    worlds = _form_pair(kv, scope, capacity)
    try:
        yield [ShmBackend(w) for w in worlds]
    finally:
        for w in worlds:
            w.close()


def _run_op_pair(backends, op: str, entries_of, response_of):
    """Run one collective on both ranks concurrently; return per-rank
    (status_or_exception, entries)."""
    out: list = [None, None]

    def run(r):
        entries = entries_of(r)
        try:
            st = getattr(backends[r], op)(response_of(r), entries)
            out[r] = (st, entries)
        except BaseException as e:  # noqa: BLE001 - captured for asserts
            out[r] = (e, entries)

    threads = [threading.Thread(target=run, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
        assert not t.is_alive(), "op hung (hang = protocol bug)"
    return out


def _f32(x):
    return np.asarray(x, dtype=np.float32)


def test_shm_alltoall_invalid_splits_sentinel(kv):
    """The -2 header sentinel (backend/shm.py alltoall): one rank's bad
    split table keeps every peer IN the lockstep and surfaces a Status
    error on ALL ranks symmetrically — the world stays formed and the
    next valid op still rides shm."""
    with _backend_pair(kv, "a2a_bad") as backends:

        def entries_of(r):
            e = TensorTableEntry(tensor_name="x",
                                 tensor=_f32(np.arange(8)))
            # Rank 0 submits a corrupt table (internal-caller path: the
            # public API rejects this at enqueue); rank 1 is valid.
            e.splits = [9, -1] if r == 0 else [4, 4]
            return [e]

        resp = Response(response_type=ResponseType.ALLTOALL,
                        tensor_names=["x"],
                        tensor_type=from_any(np.dtype(np.float32)))
        out = _run_op_pair(backends, "alltoall", entries_of,
                           lambda r: resp)
        for r in range(2):
            st = out[r][0]
            assert isinstance(st, Status) and not st.ok_p(), (r, st)
        assert backends[0].world.formed and backends[1].world.formed

        def good_entries(r):
            e = TensorTableEntry(tensor_name="y",
                                 tensor=_f32(np.arange(8) + 10 * r))
            e.splits = [4, 4]
            return [e]

        resp2 = Response(response_type=ResponseType.ALLTOALL,
                         tensor_names=["y"],
                         tensor_type=from_any(np.dtype(np.float32)))
        out = _run_op_pair(backends, "alltoall", good_entries,
                           lambda r: resp2)
        for r in range(2):
            st, entries = out[r]
            assert isinstance(st, Status) and st.ok_p(), (r, st)
            expected = np.concatenate([np.arange(4 * r, 4 * r + 4),
                                       np.arange(4 * r, 4 * r + 4) + 10])
            np.testing.assert_array_equal(entries[0].output, expected)
            assert entries[0].received_splits == [4, 4]


def test_shm_alltoall_oversized_delegates_to_tcp(kv):
    """The -1 header sentinel: ANY rank's payload exceeding the region
    capacity makes EVERY rank delegate the exchange to the TCP plane —
    the fit decision is only knowable mid-protocol, so the flag ride is
    what keeps the plane choice rank-symmetric."""
    delegated = []

    class FakeTcp:
        def alltoall(self, response, entries):
            delegated.append(True)
            for e in entries:
                e.output = np.asarray(e.tensor)
                e.received_splits = list(e.splits)
            return Status.ok()

    with _backend_pair(kv, "a2a_big", capacity=256) as backends:
        for b in backends:
            b.tcp = FakeTcp()

        def entries_of(r):
            e = TensorTableEntry(tensor_name="big",
                                 tensor=_f32(np.ones(512)))   # 2 KiB > 256 B
            e.splits = [256, 256]
            return [e]

        resp = Response(response_type=ResponseType.ALLTOALL,
                        tensor_names=["big"],
                        tensor_type=from_any(np.dtype(np.float32)))
        out = _run_op_pair(backends, "alltoall", entries_of, lambda r: resp)
        for r in range(2):
            st = out[r][0]
            assert isinstance(st, Status) and st.ok_p(), (r, st)
        assert len(delegated) == 2, "both ranks must run the TCP exchange"
        assert backends[0].world.formed     # delegation is not a failure


@pytest.mark.parametrize("op", ["allreduce", "broadcast", "allgather",
                                "alltoall", "reducescatter"])
def test_shm_poison_unblocks_each_op(kv, op):
    """A peer poisoning while this rank is inside op X's wait must
    surface a structured error for EVERY op type X — not a barrier
    timeout (reference discipline: mismatch -> error, never hang)."""
    with _backend_pair(kv, f"poison_{op}") as backends:

        def entries_of(r):
            e = TensorTableEntry(tensor_name="t",
                                 tensor=_f32(np.ones((8, 2))),
                                 root_rank=1)
            e.splits = [4, 4]
            return [e]

        kwargs = {}
        if op == "broadcast":
            # Rank 0 must be a READER: the root waits on nobody (its only
            # barrier is the entry wait, already satisfied), so a root would
            # legitimately complete — the branch under test is the reader's
            # data wait.
            kwargs["root_rank"] = 1
        sizes = {"allreduce": [16], "broadcast": [16],
                 "allgather": [8, 8], "reducescatter": [16],
                 "alltoall": []}[op]
        resp = Response(response_type=getattr(ResponseType, op.upper()),
                        tensor_names=["t"],
                        tensor_type=from_any(np.dtype(np.float32)),
                        tensor_sizes=sizes, **kwargs)

        result: list = []

        def run_rank0():
            try:
                backends[0].__getattribute__(op)(resp, entries_of(0))
                result.append("completed")
            except ConnectionError:
                result.append("poisoned")

        th = threading.Thread(target=run_rank0)
        th.start()
        # Rank 1 never claims the op; it fails "elsewhere" and poisons.
        import time
        time.sleep(0.2)
        backends[1].world.poison()
        th.join(15.0)
        assert not th.is_alive(), f"{op} hung on a poisoned world"
        assert result == ["poisoned"], result
        assert not backends[0].world.formed


def test_shm_fused_multi_tensor_allreduce(kv):
    """A fused (multi-entry) allreduce response packs through one region
    round-trip and unpacks entry-by-entry with original shapes."""
    with _backend_pair(kv, "fused_ar") as backends:

        def entries_of(r):
            return [TensorTableEntry(tensor_name=f"g{i}",
                                     tensor=_f32(np.full((3, i + 1),
                                                         r + i)))
                    for i in range(3)]

        resp = Response(response_type=ResponseType.ALLREDUCE,
                        tensor_names=["g0", "g1", "g2"],
                        tensor_type=from_any(np.dtype(np.float32)),
                        tensor_sizes=[3, 6, 9])
        out = _run_op_pair(backends, "allreduce", entries_of, lambda r: resp)
        for r in range(2):
            st, entries = out[r]
            assert isinstance(st, Status) and st.ok_p(), (r, st)
            for i, e in enumerate(entries):
                np.testing.assert_allclose(
                    e.output, np.full((3, i + 1), (0 + i) + (1 + i)))
                assert e.output.shape == (3, i + 1)


def test_shm_dead_peer_liveness_mid_wait(kv):
    """A peer DYING (not poisoning) while this rank waits surfaces the
    PID-liveness error in ~the 0.5 s poll interval, naming the dead
    rank — not the multi-minute barrier timeout."""
    scope = "deadpeer"
    child = os.fork()
    if child == 0:
        # Child = rank 1: form, then die without a word.
        try:
            w = ShmWorld(1, 2, kv, scope=scope, capacity=1 << 16,
                         timeout=15.0)
            assert w.formed
        finally:
            os._exit(0)   # abrupt death; no poison, no close
    w = ShmWorld(0, 2, kv, scope=scope, capacity=1 << 16, timeout=15.0)
    assert w.formed
    os.waitpid(child, 0)
    import time
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="died"):
        w.wait_all(1)   # rank 1 will never publish 1
    assert time.monotonic() - t0 < 10.0, "liveness poll too slow"
    w.close()
