"""ShmWorld unit tests: formation, lockstep, and the poison protocol
(fallible I/O between barrier publishes — e.g. the hierarchical cross
leg — must fail every rank fast, not hang peers until the barrier
timeout or complete with partial reductions)."""
from __future__ import annotations

import threading

import numpy as np
import pytest

from horovod_tpu.backend.shm import ShmWorld, _POISON
from horovod_tpu.runner.network import RendezvousClient, RendezvousServer


@pytest.fixture()
def kv():
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 10.0)
    server.stop()


def _form_pair(kv, scope: str, capacity: int = 1 << 16):
    """Form a 2-rank world with both ranks in one process (two instances
    attaching to each other's regions — formation needs concurrency)."""
    worlds: list = [None, None]
    errors: list = []

    def make(rank: int) -> None:
        try:
            worlds[rank] = ShmWorld(rank, 2, kv, scope=scope,
                                    capacity=capacity, timeout=10.0)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=make, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20.0)
    assert not errors, errors
    assert all(w is not None and w.formed for w in worlds), worlds
    return worlds


def test_shm_world_forms_and_steps(kv):
    a, b = _form_pair(kv, "unit1")
    try:
        a.data(0)[:4] = np.frombuffer(b"\x01\x02\x03\x04", np.uint8)
        # b reads a's region through its own mapping (shared memory).
        assert bytes(b.data(0)[:4]) == b"\x01\x02\x03\x04"
        a.publish(3)
        b.publish(3)
        a.wait_all(3)
        b.wait_all(3)
    finally:
        a.close()
        b.close()


def test_shm_poison_unblocks_waiters(kv):
    a, b = _form_pair(kv, "unit2")
    try:
        result: list = []

        def waiter():
            try:
                a.wait_all(5)
                result.append("returned")
            except ConnectionError:
                result.append("poisoned")

        th = threading.Thread(target=waiter)
        th.start()
        b.poison()
        th.join(10.0)
        assert not th.is_alive(), "waiter should have been unblocked"
        assert result == ["poisoned"]
        assert not b.formed
        assert not a.formed   # detection side also opts out of future ops
    finally:
        a.close()
        b.close()


def test_shm_poison_value_is_detectable(kv):
    a, b = _form_pair(kv, "unit3")
    try:
        b.poison()
        assert int(b._seqs[1][0]) == _POISON
        with pytest.raises(ConnectionError):
            a.wait_all(0)   # even a satisfied target reports the poison
    finally:
        a.close()
        b.close()
