"""Model family + SPMD Trainer tests on the 8-device virtual CPU mesh.

Mirrors the reference's parallel semantic tests (SURVEY §4): assert the
distributed train step produces the same result as an explicitly computed
single-device expectation, and that gradient sync keeps replicas in
lockstep.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import models, training
from horovod_tpu.parallel import (GradSyncConfig, MeshSpec, ShardingRules,
                                  build_mesh)
from jax.sharding import PartitionSpec as P


def tiny_resnet(**kw):
    return models.ResNet(stage_sizes=(1, 1), block_cls=models.resnet.BasicBlock,
                         num_classes=10, num_filters=8, dtype=jnp.float32,
                         **kw)


def test_resnet50_forward_shape():
    model = models.ResNet50(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32


def test_space_to_depth_stem_equivalent_to_conv7():
    """The folded stem is the SAME function as conv7/s2/p3: convert the
    conv7 model's stem kernel with fold_conv7_stem_weights, share every
    other parameter verbatim, and the logits must match in fp32."""
    x = jax.random.normal(jax.random.key(3), (2, 64, 64, 3), jnp.float32)
    m7 = models.ResNet18(num_classes=10, dtype=jnp.float32)
    ms = models.ResNet18(num_classes=10, dtype=jnp.float32,
                         stem="space_to_depth")
    v7 = m7.init(jax.random.key(0), x, train=False)
    vs = {**v7, "params": {
        **v7["params"],
        "conv_init": {"kernel": models.resnet.fold_conv7_stem_weights(
            v7["params"]["conv_init"]["kernel"])}}}
    np.testing.assert_allclose(
        np.asarray(ms.apply(vs, x, train=False)),
        np.asarray(m7.apply(v7, x, train=False)), atol=1e-4)


def test_space_to_depth_helpers_roundtrip():
    x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
    y = models.resnet.space_to_depth(x)
    assert y.shape == (2, 4, 4, 12)
    # cell (0,0) holds rows 0-1 x cols 0-1, channel-last within the cell
    np.testing.assert_array_equal(
        np.asarray(y[0, 0, 0]),
        np.asarray(jnp.concatenate(
            [x[0, 0, 0], x[0, 0, 1], x[0, 1, 0], x[0, 1, 1]])))


@pytest.mark.parametrize("ctor,n_params_min", [
    (models.ResNet18, 11e6), (models.ResNet50, 25e6)])
def test_param_counts(ctor, n_params_min):
    model = ctor(num_classes=1000)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 32, 32, 3)), train=False),
        jax.random.key(0))
    n = sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(
        shapes["params"]))
    assert n > n_params_min  # 11.7M / 25.6M in the torchvision models


def test_vgg16_forward_and_params():
    """VGG-16 (reference headline benchmark, docs/benchmarks.rst:13-14):
    forward shape + the torchvision-scale parameter count (~138M, its
    giant dense head is the fusion stress case)."""
    model = models.VGG16(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 1000)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 224, 224, 3)), train=False),
        jax.random.key(0))
    n = sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(
        shapes["params"]))
    assert n > 130e6


@pytest.mark.slow
def test_inception_v3_forward_and_params():
    """Inception V3 (reference headline benchmark): forward shape at the
    canonical 299px (via eval_shape — no FLOPs) and a real forward at
    96px; ~27M params in the tf-slim model.  Benchmark-class (~20s of
    real conv FLOPs on the CPU mesh), so it rides the slow tier."""
    model = models.InceptionV3(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((2, 96, 96, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 1000)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 299, 299, 3)), train=False),
        jax.random.key(0))
    n = sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(
        shapes["params"]))
    assert 20e6 < n < 35e6


@pytest.mark.parametrize("ctor,image", [
    (lambda: models.VGG16(num_classes=8, dtype=jnp.float32), 32),
    # Inception is the deepest compile of the family (its forward test
    # already rides the slow tier, round 5); the VGG16 twin keeps the
    # benchmark-family train-step surface in tier-1.
    pytest.param(
        lambda: models.InceptionV3(num_classes=8, dtype=jnp.float32), 96,
        marks=pytest.mark.slow),
])
def test_benchmark_models_train_step(ctor, image):
    """Every reference benchmark family trains under the SPMD Trainer on
    the dp mesh (fused+compressed gradient sync included).  A dp=2
    submesh: partitioning these deep graphs over all 8 virtual devices
    more than doubles XLA-CPU compile time (Inception: 220s at dp=8 vs
    98s at dp=2) without adding coverage — the 8-device sync machinery is
    exercised by the resnet Trainer tests."""
    mesh = build_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
    trainer = training.Trainer(
        ctor(), optax.sgd(0.01, momentum=0.9), mesh,
        sync=GradSyncConfig(axes=("dp",), op="average",
                            compression="fp16"))
    batch = training.synthetic_image_batch(
        4, image_size=image, num_classes=8)
    state = trainer.init(jax.random.key(0), batch)
    state, metrics = trainer.step(state, batch)
    jax.block_until_ready(metrics)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))


def test_trainer_loss_decreases():
    mesh = build_mesh(MeshSpec(dp=8))
    model = tiny_resnet()
    trainer = training.Trainer(model, optax.sgd(0.05, momentum=0.9), mesh)
    batch = training.synthetic_image_batch(16, image_size=16, num_classes=10)
    state = trainer.init(jax.random.key(0), batch)
    state, m0 = trainer.step(state, batch)
    for _ in range(10):
        state, m = trainer.step(state, batch)
    assert int(state.step) == 11
    assert float(m["loss"]) < float(m0["loss"])


def test_trainer_matches_single_device():
    """Distributed (dp=8, fused allreduce) step == single-device step.

    Sync batch norm (axis_name) makes the comparison exact: per-replica BN
    would legitimately diverge on statistics."""
    model = tiny_resnet(axis_name="dp")
    batch = training.synthetic_image_batch(16, image_size=16, num_classes=10)

    mesh8 = build_mesh(MeshSpec(dp=8))
    t8 = training.Trainer(model, optax.sgd(0.1), mesh8)
    s8 = t8.init(jax.random.key(0), batch)
    s8, _ = t8.step(s8, batch)

    mesh1 = build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    t1 = training.Trainer(model, optax.sgd(0.1), mesh1)
    s1 = t1.init(jax.random.key(0), batch)
    s1, _ = t1.step(s1, batch)

    for a, b in zip(jax.tree_util.tree_leaves(s8.params),
                    jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_trainer_compression_and_adasum_run():
    mesh = build_mesh(MeshSpec(dp=8))
    model = tiny_resnet()
    batch = training.synthetic_image_batch(8, image_size=16, num_classes=10)
    for cfg in (GradSyncConfig(axes=("dp",), op="average",
                               compression="fp16"),
                GradSyncConfig(axes=("dp",), op="adasum")):
        trainer = training.Trainer(model, optax.sgd(0.01), mesh, sync=cfg)
        state = trainer.init(jax.random.key(1), batch)
        state, metrics = trainer.step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


def test_trainer_tp_sharded_head():
    """Params sharded over tp while gradients sync over dp."""
    mesh = build_mesh(MeshSpec(dp=4, tp=2))
    rules = ShardingRules([(r"head/kernel", P(None, "tp")),
                           (r"head/bias", P("tp"))])
    model = tiny_resnet()
    trainer = training.Trainer(model, optax.sgd(0.05), mesh,
                               param_rules=rules)
    batch = training.synthetic_image_batch(8, image_size=16, num_classes=10)
    state = trainer.init(jax.random.key(0), batch)
    state, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_eval_step():
    mesh = build_mesh(MeshSpec(dp=8))
    model = tiny_resnet()
    trainer = training.Trainer(model, optax.sgd(0.05), mesh)
    batch = training.synthetic_image_batch(16, image_size=16, num_classes=10)
    state = trainer.init(jax.random.key(0), batch)
    metrics = trainer.eval_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
