"""Multi-host JAX world: hvd.init forms jax.distributed across processes
and the Trainer's dp axis spans the process boundary (VERDICT r1 item 2;
reference analogue: gloo/gloo_context.cc:136-152 rendezvous at init).

2 processes × 4 virtual CPU devices each = one dp=8 mesh; the loss after 3
steps must match a single-process dp=8 run bit-for-bit (same shards, same
math, different transport)."""
import os
import re
import subprocess
import sys

from horovod_tpu.runner.network import RendezvousServer

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "multihost_worker.py")


def _launch(rank: int, size: int, port: int, n_local: int,
            env: dict, mode: str = "dp") -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, _WORKER, str(rank), str(size), str(port),
         str(n_local), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _parse_loss(out: bytes, tag: str) -> float:
    m = re.search(rb"LOSS ([-\d.eE+]+)", out)
    assert m, f"{tag}: no LOSS line in output:\n{out.decode(errors='replace')}"
    return float(m.group(1))


# Failure signatures of HOST OVERSUBSCRIPTION, not product bugs: on this
# 1-core CI box a concurrent xdist lane can stretch a worker past its
# wall timeout or past gloo's (non-configurable) internal connect
# timeout; SIGKILL (-9) is this harness's own kill cascade. Signatures
# are matched ONLY in the failed rank's own output — a surviving peer's
# inevitable "Socket closed" noise must not whitewash another rank's
# real crash — and signal deaths other than SIGKILL (e.g. a SIGSEGV in
# native code) are product bugs, never infra.
_INFRA_SIGNATURES = (b"Connect timeout", b"coordination service",
                     b"Socket closed")


def _host_oversubscribed() -> bool:
    """Corroborating load evidence for the timeout/SIGKILL arms: a 1-min
    load average at or above the core count means a concurrent lane
    really was starving the workers."""
    try:
        return os.getloadavg()[0] >= (os.cpu_count() or 1)
    except OSError:
        return False


def _memory_pressure() -> bool:
    """A kernel OOM-kill is also SIGKILL, and an oversubscribed box is
    often ALSO memory-starved — so a -9 under memory pressure must not
    be retried away as harness infra: the workers genuinely ran the host
    out of memory (a product-weight problem, and the retry would just
    OOM again). Threshold: <5% of MemTotal available."""
    try:
        with open("/proc/meminfo") as f:
            fields = dict(line.split(":", 1) for line in f if ":" in line)
        avail_kb = int(fields["MemAvailable"].split()[0])
        total_kb = int(fields["MemTotal"].split()[0])
        return avail_kb < total_kb * 0.05
    except (OSError, KeyError, ValueError, IndexError):
        return False


def _infra_failure(failed: list, outputs: list[str]) -> bool:
    if not failed:
        return False
    for rank, rc in failed:
        own = outputs[rank].encode(errors="replace") \
            if rank < len(outputs) else b""
        has_signature = any(sig in own for sig in _INFRA_SIGNATURES)
        if rc in ("timeout", -9):
            # A bare wall timeout can equally be a genuine product
            # deadlock, and a kernel OOM-kill is also SIGKILL — neither
            # gets the silent retry unless there is corroborating
            # oversubscription evidence: a signature in the rank's own
            # output, or a load average at/above the core count.  The
            # load check alone cannot corroborate a SIGKILL: the OOM
            # killer fires on loaded hosts too, so a -9 under memory
            # pressure stays a real failure.
            if rc == -9 and _memory_pressure():
                return False
            if has_signature or _host_oversubscribed():
                continue
            return False
        if isinstance(rc, int) and rc < 0 and rc != -6:
            return False          # signal death other than SIGABRT (e.g.
                                  # SIGSEGV): a product bug, never infra
        # SIGABRT (-6) is jaxlib's LOG(FATAL) path — infra only when the
        # rank's OWN output carries an oversubscription signature (a
        # survivor outliving the torn-down coordination service);
        # likewise a nonzero exit needs a signature to count as infra.
        if not has_signature:
            return False
    return True


def _run_world(env: dict, port: int, mode: str):
    procs = [_launch(r, 2, port, 4, env, mode) for r in range(2)]
    outputs, losses, failed = [], [], []
    try:
        for r, p in enumerate(procs):
            timed_out = False
            try:
                out, _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                failed.append((r, "timeout"))
                timed_out = True
            outputs.append(f"--- rank {r} (rc={p.returncode}) ---\n"
                           + out.decode(errors="replace"))
            if timed_out:
                pass                  # already recorded as a timeout
            elif p.returncode != 0:
                failed.append((r, p.returncode))
            else:
                losses.append(_parse_loss(out, f"rank{r}"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outputs, losses, failed


def _run_mode(mode: str) -> None:
    env = dict(os.environ)
    for k in list(env):
        if k.startswith("HOROVOD_"):
            env.pop(k)

    # Single-process baseline: all 8 devices in one process.
    p = _launch(0, 1, 0, 8, env, mode)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out.decode(errors="replace")
    baseline = _parse_loss(out, "baseline")

    # 2-process run: the same mesh across 2 "hosts" of 4 devices.
    # ONE retry, strictly for oversubscription signatures (see
    # _INFRA_SIGNATURES) — a loss mismatch or clean failure is final.
    server = RendezvousServer()
    port = server.start()
    try:
        for attempt in range(2):
            env["HOROVOD_RENDEZVOUS_EPOCH"] = f"mh-{mode}-{attempt}"
            outputs, losses, failed = _run_world(env, port, mode)
            if not failed:
                break
            if attempt == 0 and _infra_failure(failed, outputs):
                # Print the failed ranks' output so a retried-away hang
                # stays visible in the log instead of being masked.
                for rank, _rc in failed:
                    if rank < len(outputs):
                        print(outputs[rank], file=sys.stderr)
                print(f"multihost {mode}: infra failure {failed}; "
                      "retrying once with a fresh epoch", file=sys.stderr)
                continue
            break
    finally:
        server.stop()
    assert not failed, "worker failures: %s\n%s" % (failed,
                                                    "\n".join(outputs))
    # Every process sees the same replicated loss, equal to the baseline.
    assert abs(losses[0] - losses[1]) < 1e-9, losses
    assert abs(losses[0] - baseline) < 1e-6, (losses, baseline)


def test_dp_axis_spans_processes():
    _run_mode("dp")


def test_hierarchical_grad_sync_hybrid_mesh():
    """Hierarchical RS → cross-AR → AG grad sync over a 2-granule hybrid
    mesh (dp across the process/DCN boundary, sp on the local leg) matches
    the single-process flat-mesh loss (VERDICT r2 item 8; reference:
    nccl_operations.cc:187-398)."""
    _run_mode("hier")


def test_multihost_trainer_fit():
    """Short multi-host Trainer.fit (2 epochs x 2 batches) with loss
    parity vs the single-process run (VERDICT r2 item 8)."""
    _run_mode("fit")
