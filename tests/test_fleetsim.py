"""fleetsim — the rank-virtualized O(500) scale harness (ISSUE 16).

- Loopback fabric units: barrier-allgather completion, arrival capture,
  idempotent transitions, removal of silently-dead members, abort.
- Host-group KV proxy units: heartbeat stamps coalesce into put_many
  batches; bye stamps bypass the buffer; snapshot reads collapse the
  per-peer poll fan-out.
- WAL group-commit coalescing at N=64 (telemetry-counter asserted).
- In-process fleet episodes: clean run, chaos kill/preempt composition
  through the UNCHANGED grammar, straggler attribution at fleet scale.
- Tier-1 smoke: one worker process hosting 32 virtual ranks against a
  real external rendezvous server (the mp battery plumbing).
- Slow battery: 256 virtual ranks riding a coordkill of the primary
  mid-run plus a 10% preemption wave — zero failed steps, bounded
  control-plane verb latency, console renders the episode.
"""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402

from horovod_tpu import telemetry  # noqa: E402
from horovod_tpu.fleetsim import (FleetConfig, FleetDesyncError,  # noqa: E402
                                  FleetSim, HostGroupSession,
                                  LoopbackFabric)
from horovod_tpu.runner import controlplane as cp  # noqa: E402
from horovod_tpu.runner.network import (RendezvousClient,  # noqa: E402
                                        RendezvousServer, free_port)


# --- loopback fabric --------------------------------------------------------
class TestLoopbackFabric:
    def test_exchange_completes_and_captures_arrivals(self):
        fab = LoopbackFabric(range(3), "e0")
        out = {}

        def body(vid):
            views, arrivals = fab.exchange("e0", 0, vid, {"v": vid}, 5.0)
            out[vid] = (views, arrivals)

        threads = [threading.Thread(target=body, args=(v,))
                   for v in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        assert set(out) == {0, 1, 2}
        for views, arrivals in out.values():
            assert {v["v"] for v in views.values()} == {0, 1, 2}
            assert set(arrivals) == {0, 1, 2}

    def test_remove_unblocks_survivors(self):
        fab = LoopbackFabric(range(2), "e0")
        got = {}

        def body():
            got["views"], _ = fab.exchange("e0", 0, 0, {"v": 0}, 5.0)

        t = threading.Thread(target=body)
        t.start()
        time.sleep(0.05)
        fab.remove(1)           # silent death: no slot ever arrives
        t.join(5.0)
        assert not t.is_alive()
        assert set(got["views"]) == {0}   # missing slot = hard failure

    def test_transition_idempotent_and_divergence_detected(self):
        fab = LoopbackFabric(range(3), "e0")
        fab.transition("e1", [0, 1])
        fab.transition("e1", [0, 1])      # second folder: verify only
        assert fab.epoch == "e1"
        with pytest.raises(FleetDesyncError):
            fab.transition("e1", [0, 2])  # divergent fold
        with pytest.raises(FleetDesyncError):
            fab.exchange("e0", 5, 0, {}, 0.1)   # stale epoch

    def test_abort_wakes_waiters(self):
        fab = LoopbackFabric(range(2), "e0")
        err = {}

        def body():
            try:
                fab.exchange("e0", 0, 0, {}, 30.0)
            except FleetDesyncError as exc:
                err["exc"] = exc

        t = threading.Thread(target=body)
        t.start()
        time.sleep(0.05)
        fab.abort()
        t.join(5.0)
        assert not t.is_alive()
        assert "aborted" in str(err["exc"])


# --- host-group KV proxy ----------------------------------------------------
class _FakeClient:
    def __init__(self):
        self.puts = []
        self.batches = []
        self.scope_reads = 0

    def put(self, scope, key, value):
        self.puts.append((scope, key, value))

    def put_many(self, records):
        self.batches.append(list(records))

    def get_scope(self, scope):
        self.scope_reads += 1
        return {"0": b"1|100"}


class TestHostGroupSession:
    def test_hb_stamps_coalesce_into_batches(self):
        client = _FakeClient()
        sess = HostGroupSession(client, group_size=4, flush_age_s=60.0)
        for vid in range(4):
            sess.put("hb", f"e:{vid}", f"{vid}|1".encode())
        assert len(client.batches) == 1       # full group -> one batch
        assert len(client.batches[0]) == 4
        assert client.puts == []

    def test_bye_stamps_bypass_the_buffer(self):
        client = _FakeClient()
        sess = HostGroupSession(client, group_size=8, flush_age_s=60.0)
        sess.put("hb", "e:0", b"bye|7")
        assert client.puts == [("hb", "e:0", b"bye|7")]
        assert client.batches == []

    def test_flush_drains_partial_buffer(self):
        client = _FakeClient()
        sess = HostGroupSession(client, group_size=8, flush_age_s=60.0)
        sess.put("hb", "e:0", b"0|1")
        sess.put("hb", "e:0", b"0|2")   # later stamp overwrites
        sess.flush()
        assert len(client.batches) == 1
        assert client.batches[0] == [("hb", "e:0", b"0|2")]

    def test_snapshot_collapses_poll_fanout(self):
        client = _FakeClient()
        sess = HostGroupSession(client, group_size=4,
                                snapshot_ttl_s=60.0)
        for _ in range(32):
            sess.snapshot_get("hb", "e:0")
        assert client.scope_reads == 1        # one dump serves them all


# --- WAL group commit -------------------------------------------------------
def test_wal_group_commit_coalesces_at_64(tmp_path):
    """ISSUE 16 satellite: one host-group put_many of 64 heartbeat
    stamps must land as 64 WAL records in a HANDFUL of fsync batches
    (the group-commit telemetry counters are the evidence)."""
    os.environ["HOROVOD_METRICS"] = "on"
    try:
        reg = telemetry.configure()

        def counter(name):
            return sum(e["value"] for e in reg.snapshot()["metrics"]
                       if e["name"] == name)

        server = RendezvousServer(wal_dir=str(tmp_path))
        port = server.start()
        try:
            client = RendezvousClient(f"127.0.0.1:{port}", timeout=10.0)
            base_records = counter(
                "horovod_rendezvous_wal_records_total")
            base_batches = counter(
                "horovod_rendezvous_wal_commit_batches_total")
            client.put_many([("hb", f"fleet:{i}", f"{i}|{os.getpid()}"
                              .encode()) for i in range(64)])
            records = counter(
                "horovod_rendezvous_wal_records_total") - base_records
            batches = counter(
                "horovod_rendezvous_wal_commit_batches_total") \
                - base_batches
            assert records == 64
            assert 1 <= batches <= 16, batches   # >=4x coalescing
            # All 64 are durable + readable (FIFO lane: the last
            # record's commit implies the rest).
            assert client.get("hb", "fleet:63") == b"63|%d" % os.getpid()
            # And survive a replay (they really hit the log).
            replayed = cp.replay_state(cp.wal_path(str(tmp_path)))
            assert replayed["kv"]["hb"]["fleet:0"] == b"0|%d" % os.getpid()
        finally:
            server.stop()
    finally:
        os.environ.pop("HOROVOD_METRICS", None)
        telemetry.configure()


# --- in-process fleet episodes ----------------------------------------------
def _in_proc_fleet(tmp_path, monkeypatch, *, ranks, steps, chaos="",
                   **cfg_kw):
    monkeypatch.setenv("HOROVOD_METRICS", "on")
    if chaos:
        monkeypatch.setenv("HOROVOD_CHAOS", chaos)
    else:
        monkeypatch.delenv("HOROVOD_CHAOS", raising=False)
    telemetry.configure()
    server = RendezvousServer()
    port = server.start()
    try:
        cfg = FleetConfig(ranks=ranks, steps=steps, step_ms=2.0,
                          heartbeat_s=0.2, fault_timeout_s=10.0,
                          step_timeout_s=30.0, host_group=8,
                          epoch=f"flt-{tmp_path.name}",
                          endpoints=f"127.0.0.1:{port}", **cfg_kw)
        fleet = FleetSim(cfg)
        return fleet.run()
    finally:
        server.stop()
        telemetry.configure()


def test_clean_episode_all_finish(tmp_path, monkeypatch):
    report = _in_proc_fleet(tmp_path, monkeypatch, ranks=12, steps=6)
    assert report.failed_steps == 0
    assert report.outcomes == {"finished": 12}
    assert report.total_rank_steps == 12 * 6
    assert report.final_world == list(range(12))
    # Host-group batching carried the liveness plane: real put_many
    # traffic was observed by the client histogram.
    assert report.kv_latency_ms.get("put_many", {}).get("count", 0) > 0


def test_chaos_grammar_composes_virtualized(tmp_path, monkeypatch):
    """The UNCHANGED chaos grammar addresses virtual ranks: a silent
    kill at step 2 and an orderly preemption at step 4 both shrink the
    fleet, with zero failed steps for the survivors."""
    report = _in_proc_fleet(
        tmp_path, monkeypatch, ranks=10, steps=8,
        chaos="kill:rank=3,op=2;preempt:rank=7,op=4")
    assert report.outcomes.get("killed") == 1
    assert report.outcomes.get("preempted") == 1
    assert report.outcomes.get("finished") == 8
    assert report.departures == {"kill": 1, "preempt": 1}
    assert report.transitions >= 2
    assert report.failed_steps == 0
    assert report.final_world == [v for v in range(10)
                                  if v not in (3, 7)]


def test_straggler_attributed_at_fleet_scale(tmp_path, monkeypatch):
    report = _in_proc_fleet(tmp_path, monkeypatch, ranks=16, steps=8,
                            straggler_vid=11, straggler_ms=40.0)
    assert report.failed_steps == 0
    assert report.straggler_rank == 11
    assert report.straggler_lag_ms > 10.0


def test_dump_evidence_roundtrips_through_console(tmp_path,
                                                  monkeypatch):
    dump_dir = tmp_path / "dumps"
    monkeypatch.setenv("HOROVOD_FLIGHT_FILE",
                       str(dump_dir / "flight.json"))
    from horovod_tpu.telemetry import flight
    flight.configure(0)
    try:
        report = _in_proc_fleet(tmp_path, monkeypatch, ranks=6, steps=4,
                                dump_dir=str(dump_dir))
        assert report.failed_steps == 0
        from horovod_tpu.console import load_dump_dir, render
        ep = load_dump_dir(str(dump_dir))
        assert not ep.empty
        assert len(ep.summaries) == 1
        text = render(ep)
        assert "ranks=6 steps=4" in text
        assert "outcomes: finished=6" in text
    finally:
        monkeypatch.delenv("HOROVOD_FLIGHT_FILE", raising=False)
        flight.configure(0)


# --- tier-1 battery: 32 virtual ranks, external control plane --------------
def _parse_summary(outputs):
    for out in outputs:
        for line in out.splitlines():
            if line.startswith("FLEETSIM_SUMMARY "):
                return json.loads(line.split(" ", 1)[1])
    raise AssertionError("no FLEETSIM_SUMMARY line:\n" + "\n".join(outputs))


def test_fleetsim_smoke_32_vranks():
    """One worker process hosts 32 virtual ranks against a real
    external rendezvous server: every rank finishes every step, the
    straggler is attributed, and the host-group batch verbs carried
    the liveness plane."""
    outputs = _run_world(
        1, "fleetsim", timeout=240.0,
        extra_env={
            "HOROVOD_FLEETSIM_RANKS": "32",
            "HOROVOD_FLEETSIM_STEPS": "8",
            "HOROVOD_FLEETSIM_STEP_MS": "2",
            "HOROVOD_FLEETSIM_HOST_GROUP": "8",
            "HOROVOD_FLEETSIM_HEARTBEAT_S": "0.2",
            "HOROVOD_FLEETSIM_FAULT_TIMEOUT_S": "15",
            "HOROVOD_FLEETSIM_STRAGGLER_RANK": "5",
            "HOROVOD_FLEETSIM_STRAGGLER_MS": "30",
        })
    s = _parse_summary(outputs)
    assert s["ranks"] == 32
    assert s["failed_steps"] == 0
    assert s["outcomes"] == {"finished": 32}
    assert s["total_rank_steps"] == 32 * 8
    assert s["straggler_rank"] == 5
    assert s["kv_latency_ms"]["put_many"]["count"] > 0
    assert s["kv_latency_ms"]["get_scope"]["count"] > 0


# --- slow battery: 256 vranks + coordkill + 10% preemption wave ------------
def _spawn_primary(tmp_path, endpoints, lease_ms=500.0):
    port = int(endpoints[0].rsplit(":", 1)[1])
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.controlplane",
         "--port", str(port), "--wal-dir", str(tmp_path),
         "--replica-id", "0", "--endpoints", ",".join(endpoints),
         "--lease-ms", str(lease_ms)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    line = proc.stdout.readline().decode()
    assert line.startswith("READY"), line
    return proc


@pytest.mark.slow
def test_fleetsim_256_coordkill_preempt_battery(tmp_path):
    """ISSUE 16 acceptance: 256 virtual ranks ride a SIGKILL of the
    rendezvous primary mid-run plus a 10% preemption wave.  Zero
    failed steps, bounded p99 on the rendezvous KV verbs (from the
    client telemetry histograms), and the console renders the full
    episode — failover, preemption departures, straggler attribution —
    from the rank-stamped dumps."""
    ports = [free_port(), free_port()]
    eps = [f"127.0.0.1:{p}" for p in ports]
    proc = _spawn_primary(tmp_path, eps, lease_ms=500.0)
    # Metrics ON in this process BEFORE the standby exists: its
    # WalWriter binds the group-commit counters here, so the test can
    # assert the post-promotion fan-in coalesced.
    os.environ["HOROVOD_METRICS"] = "on"
    reg = telemetry.configure()
    standby = RendezvousServer(port=ports[1], wal_dir=str(tmp_path),
                               replica_id=1, endpoints=eps,
                               lease_ms=500.0, standby=True)
    standby.start()
    dump_dir = tmp_path / "dumps"
    ranks, steps = 256, 10
    victims = list(range(10, 10 + ranks // 10))   # 10% wave: v10..v35
    chaos = ";".join(["coordkill:at=4"]
                     + [f"preempt:rank={v},op=6" for v in victims])
    try:
        outputs = _run_world(
            1, "fleetsim", timeout=540.0,
            extra_env={
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": ",".join(eps),
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(ports[0]),
                "HOROVOD_RENDEZVOUS_EPOCH": "fleet256",
                "HOROVOD_CHAOS": chaos,
                "HOROVOD_FLEETSIM_RANKS": str(ranks),
                "HOROVOD_FLEETSIM_STEPS": str(steps),
                "HOROVOD_FLEETSIM_STEP_MS": "5",
                "HOROVOD_FLEETSIM_HOST_GROUP": "16",
                "HOROVOD_FLEETSIM_HEARTBEAT_S": "1.0",
                "HOROVOD_FLEETSIM_FAULT_TIMEOUT_S": "60",
                "HOROVOD_FLEETSIM_STEP_TIMEOUT_S": "120",
                # 256 GIL-contended threads put the scheduling-noise
                # floor on boundary arrivals around ~100ms; the
                # injected straggler delay must dominate it for the
                # attribution to name the right rank.
                "HOROVOD_FLEETSIM_STRAGGLER_RANK": "100",
                "HOROVOD_FLEETSIM_STRAGGLER_MS": "400",
                "HOROVOD_FLEETSIM_DUMP_DIR": str(dump_dir),
            })
        s = _parse_summary(outputs)
        # Zero failed steps across the whole episode.
        assert s["failed_steps"] == 0, s
        assert s["ranks"] == ranks
        assert s["outcomes"].get("finished") == ranks - len(victims)
        assert s["outcomes"].get("preempted") == len(victims)
        assert s["departures"] == {"preempt": len(victims)}
        assert s["transitions"] >= 1
        assert len(s["final_world"]) == ranks - len(victims)
        # The coordkill really landed and the standby promoted.
        proc.wait(timeout=10)
        assert proc.returncode == -signal.SIGKILL
        assert standby.controlplane.role == "primary"
        assert standby.controlplane.failovers == 1
        assert s["primaries_seen"] == eps    # both replicas led
        # Bounded control-plane latency THROUGH the failover: p99 per
        # rendezvous KV verb from the client-side histograms.
        lat = s["kv_latency_ms"]
        assert lat["put_many"]["count"] > 0
        for verb, row in lat.items():
            assert row["p99"] < 15000.0, (verb, row)
        for verb in ("put_many", "get_scope"):
            assert lat[verb]["p99"] < 8000.0, (verb, lat[verb])
        # WAL group commit coalesced the fleet's liveness fan-in: the
        # promoted standby's lane counters live in THIS process.
        def counter(name):
            return sum(e["value"] for e in reg.snapshot()["metrics"]
                       if e["name"] == name)
        records = counter("horovod_rendezvous_wal_records_total")
        batches = counter("horovod_rendezvous_wal_commit_batches_total")
        assert records > 0
        assert batches <= records
        # Console renders the full episode from the rank-stamped dumps.
        from horovod_tpu.console import (load_dump_dir, render,
                                         summary_lines)
        ep = load_dump_dir(str(dump_dir))
        assert not ep.empty
        text = render(ep)
        assert f"ranks={ranks} steps={steps}" in text
        assert "failovers: 1" in text
        assert f"preempted={len(victims)}" in text
        assert "rank=100" in text            # straggler attribution
        lines = summary_lines(ep)
        assert any("failovers=1" in line for line in lines)
        assert any(f"preempt={len(victims)}" in line
                   for line in lines)
    finally:
        if proc.poll() is None:
            proc.kill()
        standby.stop()
        os.environ.pop("HOROVOD_METRICS", None)
        telemetry.configure()
