"""Cross-rank tracing + flight recorder tests (ISSUE 7): trace-id
stamping end to end, per-rank timeline merge with clock offsets and
flow links, critical-path attribution, the merged-trace golden fixture
through telemetry.report, and the flight recorder's ring/dump/off-mode
contracts."""
from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

from horovod_tpu.telemetry import flight as flight_mod
from horovod_tpu.telemetry import trace as trace_mod
from horovod_tpu.telemetry.report import summarize_file, summarize_timeline

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "telemetry")


# ---------------------------------------------------------------------------
# Synthetic per-rank timeline files
# ---------------------------------------------------------------------------
def _write_rank_files(tmp_path, ranks=4, collectives=3, slow_rank=2,
                      delay_us=8000):
    """Deterministic per-rank files: `slow_rank` negotiates late on
    every collective (its op span starts last); clock offsets/bases
    differ per rank so alignment is actually exercised."""
    paths = []
    for r in range(ranks):
        ev = [{"name": "horovod_clock_sync", "ph": "M", "pid": 0,
               "args": {"rank": r, "start_us": 1_000_000.0 + 50.0 * r,
                        "clock_offset_us": -50.0 * r,
                        "clock_rtt_us": 30.0 + r}}]
        for k in range(collectives):
            trace = f"{k + 2}.0"
            base = 10_000 * k
            delay = delay_us if r == slow_rank else 0
            ev.append({"name": "QUEUE", "cat": "op_queue", "ph": "b",
                       "id": k, "ts": base + 10, "pid": 0, "tid": 0})
            ev.append({"name": "NEGOTIATE_ALLREDUCE", "ph": "B",
                       "ts": base + 20, "pid": 0, "tid": 0})
            ev.append({"name": "", "ph": "E", "ts": base + 500 + delay,
                       "pid": 0, "tid": 0, "args": {"trace": trace}})
            op_b = base + 520 + delay
            op_e = base + 4600 + delay_us  # ring completes together
            ev.append({"name": "ALLREDUCE", "ph": "B", "ts": op_b,
                       "pid": 0, "tid": 0, "args": {"trace": trace}})
            ev.append({"name": "TCP_RING_ALLREDUCE", "ph": "B",
                       "ts": op_b + 30, "pid": 0, "tid": 0,
                       "args": {"trace": trace}})
            ev.append({"name": "", "ph": "E", "ts": op_e - 40, "pid": 0,
                       "tid": 0})
            ev.append({"name": "", "ph": "E", "ts": op_e, "pid": 0,
                       "tid": 0})
            ev.append({"name": "QUEUE", "cat": "op_queue", "ph": "e",
                       "id": k, "ts": op_e + 25, "pid": 0, "tid": 0,
                       "args": {"trace": trace}})
        p = tmp_path / (f"t.r{r}.json" if r else "t.json")
        p.write_text(json.dumps(ev))
        paths.append(str(p))
    return paths


# ---------------------------------------------------------------------------
# trace module: load / merge / critical path
# ---------------------------------------------------------------------------
def test_load_reads_clock_metadata_and_aligns(tmp_path):
    paths = _write_rank_files(tmp_path)
    traces = trace_mod.load(paths)
    assert [t.rank for t in traces] == [0, 1, 2, 3]
    assert traces[2].clock_offset_us == -100.0
    assert traces[1].clock_rtt_us == 31.0
    # start_us + offset_us is the alignment base; all four land on the
    # same coordinator clock here (base rises 50/rank, offset -50/rank),
    # so every shift is identical (minimum-normalized to 0).
    assert [t.shift_us for t in traces] == [0.0, 0.0, 0.0, 0.0]


def test_load_rank_fallback_from_filename(tmp_path):
    p = tmp_path / "legacy.r3.json"
    p.write_text(json.dumps([{"name": "ALLREDUCE", "ph": "B", "ts": 0,
                              "pid": 0, "tid": 0}]))
    assert trace_mod.load_rank_file(str(p)).rank == 3


def test_merge_rewrites_pids_and_links_flows(tmp_path):
    paths = _write_rank_files(tmp_path)
    merged = trace_mod.merge(trace_mod.load(paths))
    pids = {e.get("pid") for e in merged if e.get("ph") == "B"}
    assert pids == {0, 1, 2, 3}
    flows = [e for e in merged if e.get("ph") in ("s", "f")]
    by_id: dict = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    # Every collective is flow-linked across all 4 ranks: one source
    # ("s") + three bind points ("f").
    assert set(by_id) == {"2.0", "3.0", "4.0"}
    for evs in by_id.values():
        assert sorted(e["ph"] for e in evs) == ["f", "f", "f", "s"]
        assert {e["pid"] for e in evs} == {0, 1, 2, 3}
        # The source is the earliest op span — never the delayed rank.
        src = next(e for e in evs if e["ph"] == "s")
        assert src["pid"] != 2


def test_critical_path_names_delayed_rank_and_phase(tmp_path):
    paths = _write_rank_files(tmp_path, slow_rank=2)
    report = trace_mod.critical_path_report(trace_mod.load(paths),
                                            window=8)
    assert "critical path: rank 2, phase negotiate" in report, report
    assert "bottleneck rank 2 (3/3)" in report


def test_critical_path_phase_decomposition(tmp_path):
    paths = _write_rank_files(tmp_path, ranks=2, collectives=1,
                              slow_rank=1, delay_us=2000)
    records = trace_mod.collective_records(trace_mod.load(paths))
    assert set(records) == {"2.0"}
    r1 = records["2.0"][1]
    # negotiate spans the injected delay; wire is the nested ring span.
    assert r1.phases["negotiate"] == pytest.approx(2480, abs=1)
    assert r1.phases["wire"] > 0
    assert r1.phases["framework"] >= 0
    assert r1.op_end > r1.op_start


def test_critical_path_empty_input_is_graceful(tmp_path):
    p = tmp_path / "solo.json"
    p.write_text(json.dumps([{"name": "horovod_clock_sync", "ph": "M",
                              "pid": 0, "args": {"rank": 0,
                                                 "start_us": 0.0}}]))
    report = trace_mod.critical_path_report(
        trace_mod.load([str(p)]), window=4)
    assert "no cross-rank collectives" in report


def test_load_rejects_duplicate_ranks(tmp_path):
    paths = _write_rank_files(tmp_path, ranks=1)
    with pytest.raises(ValueError, match="duplicate rank"):
        trace_mod.load([paths[0], paths[0]])


def test_trace_cli_writes_merged_and_report(tmp_path, capsys):
    paths = _write_rank_files(tmp_path)
    out = tmp_path / "merged.json"
    rc = trace_mod.main(paths + ["-o", str(out), "--critical-path",
                                 "--window", "8"])
    assert rc == 0
    assert "critical path: rank 2" in capsys.readouterr().out
    merged = json.loads(out.read_text())
    assert any(e.get("ph") == "s" for e in merged)


# ---------------------------------------------------------------------------
# golden fixture: merged 4-rank trace through telemetry.report
# ---------------------------------------------------------------------------
def test_report_summarizes_merged_trace_golden_fixture():
    """The merged trace (flow events present, pid=rank) still feeds the
    per-activity summarizer: B/E spans match as before, s/f flow events
    are ignored rather than corrupting the span stacks."""
    path = os.path.join(FIXTURES, "merged_trace.json")
    events = json.loads(open(path).read())
    assert any(e.get("ph") == "s" for e in events)   # flows ARE present
    out = summarize_timeline(events)
    assert "ALLREDUCE" in out and "TCP_RING_ALLREDUCE" in out
    # 4 ranks x 3 collectives = 12 op spans survive the flow events.
    row = next(line for line in out.splitlines()
               if line.startswith("ALLREDUCE"))
    assert row.split()[1] == "12", row
    assert "tensor_queue_depth" in out
    assert "(merged" not in summarize_file(path)   # parses as timeline


def test_golden_fixture_critical_path_is_stable(tmp_path):
    """Regenerating the attribution from the committed fixture's source
    shape keeps naming rank 2 / negotiate — the documented worked
    example (docs/observability.md) stays truthful."""
    paths = _write_rank_files(tmp_path)
    report = trace_mod.critical_path_report(trace_mod.load(paths), 8)
    assert "rank 2" in report and "negotiate" in report


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_flight_ring_is_bounded_and_dumps(tmp_path):
    rec = flight_mod.FlightRecorder(3, capacity=16,
                                    path=str(tmp_path / "f.json"))
    assert rec.enabled
    for i in range(100):
        rec.record("dispatch", f"t{i}", trace=f"1.{i}", detail="x")
    snap = rec.snapshot()
    assert len(snap) == 16                      # bounded ring
    assert snap[-1]["name"] == "t99"            # tail is most recent
    rec.set_metadata(clock_offset_us=12.0)
    path = rec.dump(reason="unit")
    assert path == str(tmp_path / "f.json")
    payload = json.loads(open(path).read())
    assert payload["rank"] == 3
    assert payload["reason"] == "unit"
    assert payload["meta"]["clock_offset_us"] == 12.0
    assert len(payload["events"]) == 16
    assert payload["events"][-1]["trace"] == "1.99"
    assert rec.dumps == 1 and rec.last_dump_path == path


def test_flight_dump_failure_never_raises(tmp_path):
    rec = flight_mod.FlightRecorder(0, 8,
                                    str(tmp_path / "no" / "dir" / "f"))
    rec.record("x")
    assert rec.dump(reason="r") is None          # unwritable: swallowed


def test_flight_off_mode_is_null(monkeypatch):
    monkeypatch.setenv("HOROVOD_FLIGHT", "0")
    rec = flight_mod.configure(1)
    assert rec is flight_mod.NULL_FLIGHT
    assert not rec.enabled
    rec.record("x", "y")
    assert rec.dump(reason="z") is None
    assert rec.snapshot() == []
    assert flight_mod.recorder() is flight_mod.NULL_FLIGHT


def test_flight_configure_uses_env(monkeypatch, tmp_path):
    monkeypatch.delenv("HOROVOD_FLIGHT", raising=False)
    monkeypatch.setenv("HOROVOD_FLIGHT_EVENTS", "32")
    monkeypatch.setenv("HOROVOD_FLIGHT_FILE",
                       str(tmp_path / "fl_{rank}.json"))
    from census import assert_no_new_threads, thread_names
    before = thread_names()
    rec = flight_mod.configure(2)
    assert rec.enabled
    assert rec.path == str(tmp_path / "fl_2.json")
    assert rec._ring.maxlen == 32
    # The recorder never owns a thread (zero-overhead contract).
    assert_no_new_threads(before, context="flight configure")


def test_flight_sigterm_handler_chained(monkeypatch, tmp_path):
    import signal

    monkeypatch.delenv("HOROVOD_FLIGHT", raising=False)
    monkeypatch.setenv("HOROVOD_FLIGHT_FILE",
                       str(tmp_path / "sig.json"))
    rec = flight_mod.configure(0)
    assert signal.getsignal(signal.SIGTERM) is flight_mod._sigterm_handler
    # The handler dumps, then defers to the previous disposition.
    called = []
    flight_mod._prev_sigterm, prev = (lambda *a: called.append(a)), \
        flight_mod._prev_sigterm
    try:
        flight_mod._sigterm_handler(signal.SIGTERM, None)
    finally:
        flight_mod._prev_sigterm = prev
    assert called and os.path.exists(rec.path), rec.path
    payload = json.loads(open(rec.path).read())
    assert payload["reason"] == "SIGTERM"


# ---------------------------------------------------------------------------
# end to end: trace ids + queue spans + flight in a real (1-rank) world
# ---------------------------------------------------------------------------
def test_trace_ids_and_queue_spans_end_to_end(monkeypatch, tmp_path):
    import horovod_tpu as hvd
    from horovod_tpu import core

    tl_path = tmp_path / "e2e.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(tl_path))
    monkeypatch.setenv("HOROVOD_FLIGHT_FILE",
                       str(tmp_path / "fl.json"))
    monkeypatch.delenv("HOROVOD_FLIGHT", raising=False)
    hvd.init()
    try:
        st = core.global_state()
        assert st.flight.enabled
        for i in range(3):
            hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum,
                          name=f"e2e_{i}")
        kinds = [e["kind"] for e in st.flight.snapshot()]
        assert "enqueue" in kinds and "dispatch" in kinds \
            and "done" in kinds
        traced = [e for e in st.flight.snapshot()
                  if e["kind"] == "dispatch"]
        assert all(e["trace"] for e in traced)
    finally:
        hvd.shutdown()

    events = json.loads(tl_path.read_text())
    ops = [e for e in events
           if e.get("ph") == "B" and e.get("name") == "ALLREDUCE"]
    assert len(ops) == 3
    ids = [e["args"]["trace"] for e in ops]
    assert len(set(ids)) == 3                       # fresh id per op
    assert ids == sorted(ids, key=trace_mod._sort_key)  # monotone
    qb = [e for e in events if e.get("ph") == "b"]
    qe = [e for e in events if e.get("ph") == "e"]
    assert len(qb) == len(qe) == 3
    assert all(e["args"]["trace"] for e in qe)
    # Single-file load works (no flows for a 1-rank world).
    traces = trace_mod.load([str(tl_path)])
    assert traces[0].rank == 0
    assert not any(e.get("ph") == "s"
                   for e in trace_mod.merge(traces))


def test_flight_off_world_thread_census(monkeypatch):
    """HOROVOD_FLIGHT=0 + HOROVOD_METRICS off: the exact zero-overhead
    posture — Null recorder, no new threads beyond the background
    loop (the ISSUE 7 acceptance census)."""
    monkeypatch.setenv("HOROVOD_FLIGHT", "0")
    monkeypatch.delenv("HOROVOD_METRICS", raising=False)
    import horovod_tpu as hvd
    from horovod_tpu import core

    from census import assert_no_new_threads, thread_names
    before = thread_names()
    hvd.init()
    try:
        st = core.global_state()
        assert st.flight is flight_mod.NULL_FLIGHT
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name="fl_off")
        np.testing.assert_allclose(out, np.ones(4))
        assert_no_new_threads(before, allow={"hvd-background"},
                              context="flight-off world")
    finally:
        hvd.shutdown()
