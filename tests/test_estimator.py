"""Spark-ML-style Estimator facade (VERDICT r1 item 7 / r2 item 7).

Reference: horovod/spark/torch/estimator.py:91-328 + spark/common/store.py.
Runs on pandas DataFrames (pyspark absent in this image) over real forked
workers via horovod_tpu.run — fit() must train distributed (2 ranks),
persist the model through the Store (parameterized over the local
FilesystemStore AND the network RemoteBlobStore, the HDFSStore slot), and
transform() must append prediction columns.
"""
import numpy as np
import pandas as pd
import pytest

from horovod_tpu.spark import (FilesystemStore, KVBlobClient,
                               RemoteBlobStore)


def _linear_df(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 3)).astype(np.float32)
    w = np.array([1.5, -2.0, 0.5], np.float32)
    y = x @ w + 0.1
    return pd.DataFrame({
        "f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "label": y})


@pytest.fixture(params=["filesystem", "remote_kv"])
def store(request, tmp_path):
    """Both store families: every estimator test must pass with artifacts
    on a local directory AND behind the network blob store (workers then
    exchange data/checkpoints with no shared filesystem assumption)."""
    if request.param == "filesystem":
        yield FilesystemStore(str(tmp_path / "store"))
        return
    from horovod_tpu.runner.network import RendezvousServer
    server = RendezvousServer()
    port = server.start()
    try:
        yield RemoteBlobStore(KVBlobClient("127.0.0.1", port), "est")
    finally:
        server.stop()


def test_store_layout(tmp_path):
    store = FilesystemStore(str(tmp_path / "store"))
    run_id = store.new_run_id()
    ckpt = store.get_checkpoint_path(run_id)
    data = store.get_train_data_path(run_id)
    assert ckpt.startswith(store.get_run_path(run_id))
    assert data != ckpt
    store.save_object(f"{ckpt}/meta.pkl", {"epoch": 3})
    assert store.load_object(f"{ckpt}/meta.pkl") == {"epoch": 3}
    store.cleanup_run(run_id)
    import os
    assert not os.path.exists(store.get_run_path(run_id) + "/checkpoints")


def test_remote_store_roundtrip(store):
    """Byte/object/npz round-trips through whichever store family."""
    run_id = store.new_run_id()
    ckpt = store.get_checkpoint_path(run_id)
    key = store.join(ckpt, "meta.pkl")
    store.save_object(key, {"epoch": 3})
    assert store.load_object(key) == {"epoch": 3}
    assert store.exists(key)
    assert not store.exists(store.join(ckpt, "missing"))
    store.save_npz(store.join(ckpt, "a.npz"), x=np.arange(5))
    np.testing.assert_array_equal(
        store.load_npz(store.join(ckpt, "a.npz"))["x"], np.arange(5))


def test_store_create_dispatch(tmp_path):
    from horovod_tpu.spark import Store
    assert isinstance(Store.create(str(tmp_path / "s")), FilesystemStore)
    remote = Store.create("kv://127.0.0.1:9/pfx")
    assert isinstance(remote, RemoteBlobStore)
    assert remote.prefix == "pfx"
    with pytest.raises(ValueError, match="hdfs"):
        Store.create("hdfs://nn:8020/path")


def test_lightning_estimator_is_documented_cut():
    from horovod_tpu.spark import LightningEstimator
    with pytest.raises(ImportError, match="scope cut"):
        LightningEstimator(model=None)


def test_torch_estimator_fit_transform(store):
    torch = pytest.importorskip("torch")
    from horovod_tpu.spark import TorchEstimator

    torch.manual_seed(0)
    model = torch.nn.Linear(3, 1)
    df = _linear_df()
    import functools
    est = TorchEstimator(
        model=model,
        optimizer=functools.partial(torch.optim.SGD, lr=0.2),
        loss="mse", feature_cols=["f0", "f1", "f2"],
        label_cols=["label"], batch_size=16, epochs=20, num_proc=2,
        store=store)
    trained = est.fit(df)

    # Distributed training converged on the linear target.
    assert trained.history[-1] < trained.history[0]
    assert trained.history[-1] < 0.05

    out = trained.transform(df)
    assert "label__output" in out.columns
    err = np.mean((out["label__output"].to_numpy()
                   - df["label"].to_numpy()) ** 2)
    assert err < 0.05


def test_keras_estimator_fit_transform(store):
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.spark import KerasEstimator

    tf.keras.utils.set_random_seed(1)
    model = tf.keras.Sequential([tf.keras.layers.Input(shape=(3,)),
                                 tf.keras.layers.Dense(1)])
    df = _linear_df()
    est = KerasEstimator(
        model=model, optimizer="sgd", loss="mse",
        feature_cols=["f0", "f1", "f2"], label_cols=["label"],
        batch_size=16, epochs=15, num_proc=2,
        store=store)
    trained = est.fit(df)
    losses = trained.history.get("loss", [])
    assert losses and losses[-1] < losses[0]

    out = trained.transform(df)
    assert "label__output" in out.columns
