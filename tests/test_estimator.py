"""Spark-ML-style Estimator facade (VERDICT r1 item 7 / r2 item 7).

Reference: horovod/spark/torch/estimator.py:91-328 + spark/common/store.py.
Runs on pandas DataFrames (pyspark absent in this image) over real forked
workers via horovod_tpu.run — fit() must train distributed (2 ranks),
persist the model through the Store (parameterized over the local
FilesystemStore AND the network RemoteBlobStore, the HDFSStore slot), and
transform() must append prediction columns.
"""
import numpy as np
import pandas as pd
import pytest

from horovod_tpu.spark import (FilesystemStore, KVBlobClient,
                               RemoteBlobStore)


def _linear_df(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 3)).astype(np.float32)
    w = np.array([1.5, -2.0, 0.5], np.float32)
    y = x @ w + 0.1
    return pd.DataFrame({
        "f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "label": y})


@pytest.fixture(params=[
    # Tier-1 wall clock (round 6): the estimator logic is store-agnostic
    # and remote_kv exercises strictly more machinery (KV client+server
    # on top of the same artifact protocol), so the filesystem half of
    # every fixture user rides the slow tier; FilesystemStore mechanics
    # stay in tier-1 via test_store_layout.
    pytest.param("filesystem", marks=pytest.mark.slow),
    "remote_kv",
])
def store(request, tmp_path):
    """Both store families: every estimator test must pass with artifacts
    on a local directory AND behind the network blob store (workers then
    exchange data/checkpoints with no shared filesystem assumption)."""
    if request.param == "filesystem":
        yield FilesystemStore(str(tmp_path / "store"))
        return
    from horovod_tpu.runner.network import RendezvousServer
    server = RendezvousServer()
    port = server.start()
    try:
        yield RemoteBlobStore(KVBlobClient("127.0.0.1", port), "est")
    finally:
        server.stop()


def test_store_layout(tmp_path):
    store = FilesystemStore(str(tmp_path / "store"))
    run_id = store.new_run_id()
    ckpt = store.get_checkpoint_path(run_id)
    data = store.get_train_data_path(run_id)
    assert ckpt.startswith(store.get_run_path(run_id))
    assert data != ckpt
    store.save_object(f"{ckpt}/meta.pkl", {"epoch": 3})
    assert store.load_object(f"{ckpt}/meta.pkl") == {"epoch": 3}
    store.cleanup_run(run_id)
    import os
    assert not os.path.exists(store.get_run_path(run_id) + "/checkpoints")


def test_remote_store_roundtrip(store):
    """Byte/object/npz round-trips through whichever store family."""
    run_id = store.new_run_id()
    ckpt = store.get_checkpoint_path(run_id)
    key = store.join(ckpt, "meta.pkl")
    store.save_object(key, {"epoch": 3})
    assert store.load_object(key) == {"epoch": 3}
    assert store.exists(key)
    assert not store.exists(store.join(ckpt, "missing"))
    store.save_npz(store.join(ckpt, "a.npz"), x=np.arange(5))
    np.testing.assert_array_equal(
        store.load_npz(store.join(ckpt, "a.npz"))["x"], np.arange(5))


def test_store_create_dispatch(tmp_path):
    from horovod_tpu.spark import Store
    assert isinstance(Store.create(str(tmp_path / "s")), FilesystemStore)
    remote = Store.create("kv://127.0.0.1:9/pfx")
    assert isinstance(remote, RemoteBlobStore)
    assert remote.prefix == "pfx"
    with pytest.raises(ValueError, match="hdfs"):
        Store.create("hdfs://nn:8020/path")


def test_lightning_estimator_rejects_plain_module():
    torch = pytest.importorskip("torch")
    from horovod_tpu.spark import LightningEstimator
    with pytest.raises(ValueError, match="training_step"):
        LightningEstimator(model=torch.nn.Linear(3, 1))


def test_lightning_estimator_fit_transform(store, monkeypatch):
    """The LightningModule protocol (training_step/configure_optimizers/
    on_train_epoch_end, scheduler tuple form) drives distributed fit
    (reference: spark/lightning/estimator.py)."""
    pytest.importorskip("torch")
    import os as _os
    tests_dir = _os.path.dirname(_os.path.abspath(__file__))
    monkeypatch.setenv(
        "PYTHONPATH",
        tests_dir + _os.pathsep + _os.environ.get("PYTHONPATH", ""))
    import torch
    from lit_module import LinearLit

    from horovod_tpu.spark import LightningEstimator

    torch.manual_seed(0)
    df = _linear_df()
    est = LightningEstimator(
        model=LinearLit(3), feature_cols=["f0", "f1", "f2"],
        label_cols=["label"], batch_size=16, epochs=20, num_proc=2,
        store=store)
    trained = est.fit(df)

    assert trained.history[-1] < trained.history[0]
    assert trained.history[-1] < 0.05
    assert trained.model.epochs_ended == 20   # hook ran every epoch

    out = trained.transform(df)
    assert "label__output" in out.columns
    err = np.mean((out["label__output"].to_numpy()
                   - df["label"].to_numpy()) ** 2)
    assert err < 0.05


def test_torch_estimator_fit_transform(store):
    torch = pytest.importorskip("torch")
    from horovod_tpu.spark import TorchEstimator

    torch.manual_seed(0)
    model = torch.nn.Linear(3, 1)
    df = _linear_df()
    import functools
    est = TorchEstimator(
        model=model,
        optimizer=functools.partial(torch.optim.SGD, lr=0.2),
        loss="mse", feature_cols=["f0", "f1", "f2"],
        label_cols=["label"], batch_size=16, epochs=20, num_proc=2,
        store=store)
    trained = est.fit(df)

    # Distributed training converged on the linear target.
    assert trained.history[-1] < trained.history[0]
    assert trained.history[-1] < 0.05

    out = trained.transform(df)
    assert "label__output" in out.columns
    err = np.mean((out["label__output"].to_numpy()
                   - df["label"].to_numpy()) ** 2)
    assert err < 0.05


def test_keras_estimator_fit_transform(store):
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.spark import KerasEstimator

    tf.keras.utils.set_random_seed(1)
    model = tf.keras.Sequential([tf.keras.layers.Input(shape=(3,)),
                                 tf.keras.layers.Dense(1)])
    df = _linear_df()
    est = KerasEstimator(
        model=model, optimizer="sgd", loss="mse",
        feature_cols=["f0", "f1", "f2"], label_cols=["label"],
        batch_size=16, epochs=15, num_proc=2,
        store=store)
    trained = est.fit(df)
    losses = trained.history.get("loss", [])
    assert losses and losses[-1] < losses[0]

    out = trained.transform(df)
    assert "label__output" in out.columns


def test_unpack_configure_optimizers_forms():
    torch = pytest.importorskip("torch")
    from horovod_tpu.spark.estimator import _unpack_configure_optimizers

    p = [torch.nn.Parameter(torch.zeros(2))]
    opt = torch.optim.SGD(p, lr=0.1)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1)
    assert _unpack_configure_optimizers(opt) == (opt, [])
    assert _unpack_configure_optimizers([opt]) == (opt, [])
    assert _unpack_configure_optimizers(([opt], [sched])) \
        == (opt, [(sched, "epoch")])
    assert _unpack_configure_optimizers(
        ([opt], [{"scheduler": sched, "interval": "step"}])) \
        == (opt, [(sched, "step")])
    assert _unpack_configure_optimizers(
        {"optimizer": opt, "lr_scheduler": sched}) \
        == (opt, [(sched, "epoch")])
    assert _unpack_configure_optimizers({"optimizer": opt}) == (opt, [])
    # Multi-optimizer (GAN-style) raises instead of silently dropping.
    opt2 = torch.optim.SGD(p, lr=0.2)
    with pytest.raises(NotImplementedError, match="2 optimizers"):
        _unpack_configure_optimizers([opt, opt2])
    with pytest.raises(NotImplementedError, match="2 optimizers"):
        _unpack_configure_optimizers(([opt, opt2], []))


def test_torch_estimator_uneven_rows(tmp_path):
    """n % (num_proc * batch_size) != 0: the equalized wrap-around shard
    keeps every rank's collective count identical (unequal counts
    deadlock the negotiation — this test hung before the fix)."""
    torch = pytest.importorskip("torch")
    import functools

    from horovod_tpu.spark import TorchEstimator

    torch.manual_seed(0)
    est = TorchEstimator(
        model=torch.nn.Linear(3, 1),
        optimizer=functools.partial(torch.optim.SGD, lr=0.2),
        loss="mse", feature_cols=["f0", "f1", "f2"],
        label_cols=["label"], batch_size=16, epochs=4, num_proc=2,
        store=FilesystemStore(str(tmp_path / "store")))
    trained = est.fit(_linear_df(n=33))
    assert trained.history[-1] < trained.history[0]


def test_lightning_estimator_dict_optimizer_form(tmp_path, monkeypatch):
    pytest.importorskip("torch")
    import os as _os
    tests_dir = _os.path.dirname(_os.path.abspath(__file__))
    monkeypatch.setenv(
        "PYTHONPATH",
        tests_dir + _os.pathsep + _os.environ.get("PYTHONPATH", ""))
    import torch
    from lit_module import DictLit

    from horovod_tpu.spark import LightningEstimator

    torch.manual_seed(0)
    est = LightningEstimator(
        model=DictLit(3), feature_cols=["f0", "f1", "f2"],
        label_cols=["label"], batch_size=16, epochs=10, num_proc=2,
        store=FilesystemStore(str(tmp_path / "store")))
    trained = est.fit(_linear_df(n=48))
    assert trained.history[-1] < trained.history[0]


def test_lightning_scheduler_drives_training(tmp_path, monkeypatch):
    """Regression: schedulers must act on the optimizer that actually
    steps (rebinding after the DistributedOptimizer wrap). The LR is
    zeroed after epoch 1, so the loss must stop improving — an inert
    scheduler keeps training and converges."""
    pytest.importorskip("torch")
    import os as _os
    tests_dir = _os.path.dirname(_os.path.abspath(__file__))
    monkeypatch.setenv(
        "PYTHONPATH",
        tests_dir + _os.pathsep + _os.environ.get("PYTHONPATH", ""))
    import torch
    from lit_module import FreezeAfterOneLit

    from horovod_tpu.spark import LightningEstimator

    torch.manual_seed(0)
    est = LightningEstimator(
        model=FreezeAfterOneLit(3), feature_cols=["f0", "f1", "f2"],
        label_cols=["label"], batch_size=16, epochs=8, num_proc=2,
        store=FilesystemStore(str(tmp_path / "store")))
    trained = est.fit(_linear_df(n=64))
    h = trained.history
    # Epoch 0 trains (loss drops); epochs >= 2 are frozen at epoch-1's
    # loss. An inert scheduler would keep converging toward ~0.
    assert h[1] < h[0]
    assert h[-1] == pytest.approx(h[2], rel=1e-5)
    assert h[-1] > 0.001
