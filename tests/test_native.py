"""Native C++ kernel tests: build, pack, and the fd-level ring allreduce
(driven over real socketpairs, no launcher involved)."""
from __future__ import annotations

import socket
import threading

import numpy as np
import pytest

from horovod_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_pack_matches_concatenate():
    rng = np.random.default_rng(0)
    parts = [rng.standard_normal(n).astype(np.float32)
             for n in (3, 17, 1, 64)]
    sizes = [p.size for p in parts]
    fused = native.pack(list(parts), sizes, np.dtype(np.float32))
    np.testing.assert_array_equal(fused, np.concatenate(parts))


def test_pack_zero_fills_joined_ranks():
    parts = [np.ones(4, np.float32), None, np.full(2, 3.0, np.float32)]
    fused = native.pack(parts, [4, 5, 2], np.dtype(np.float32))
    np.testing.assert_array_equal(
        fused, np.concatenate([np.ones(4), np.zeros(5), np.full(2, 3.0)])
        .astype(np.float32))


def _ring_world(size: int):
    """Full-duplex ring: sock[i][0] connects rank i -> rank (i+1)%size."""
    pairs = [socket.socketpair() for _ in range(size)]
    for a, b in pairs:
        a.settimeout(30)
        b.settimeout(30)
    # rank r: send_fd = pairs[r][0] (to next), recv_fd = pairs[r-1][1]
    return pairs


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64])
@pytest.mark.parametrize("size,n", [(2, 7), (3, 1000), (4, 64)])
def test_ring_allreduce_fd(dtype, size, n):
    pairs = _ring_world(size)
    inputs = [np.arange(n, dtype=dtype) * (r + 1) for r in range(size)]
    expected = np.sum(inputs, axis=0).astype(dtype)
    results = [None] * size
    errors = []

    def worker(r):
        buf = inputs[r].copy()
        send_fd = pairs[r][0].fileno()
        recv_fd = pairs[(r - 1) % size][1].fileno()
        try:
            ok = native.ring_allreduce(send_fd, recv_fd, buf, r, size)
            assert ok
            results[r] = buf
        except BaseException as e:  # noqa: BLE001
            errors.append((r, e))

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for a, b in pairs:
        a.close()
        b.close()
    assert not errors, errors
    for r in range(size):
        np.testing.assert_array_equal(results[r], expected)


def test_ring_allreduce_rejects_unsupported_dtype():
    buf = np.ones(4, np.float16)
    assert native.ring_allreduce(0, 0, buf, 0, 2) is False
