"""Sitecustomize shim for spawned test-worker processes.

An environment-level sitecustomize (e.g. an accelerator-tunnel site
earlier on PYTHONPATH) may import jax and force-register its PJRT
plugin in EVERY python process, then override platform selection with
``jax.config.update("jax_platforms", ...)`` — which supersedes the
``JAX_PLATFORMS=cpu`` env var the test suite sets for its virtual CPU
mesh.  In-process, tests/conftest.py flips the config back; spawned
worker subprocesses (multiprocess batteries, estimators, multihost
workers) never import conftest, so without this shim they would
silently run jax work on the real accelerator AND pay the per-process
plugin registration/dial cost (~3-6 s each).

conftest.py prepends this file's directory to PYTHONPATH so children
import THIS module as ``sitecustomize`` instead: when the caller asked
for CPU (JAX_PLATFORMS starts with "cpu"), accelerator registration is
skipped entirely and the env var works as documented; otherwise the
original sitecustomize is chained so accelerator-backed children (e.g.
an on-TPU bench spawned from a test shell) behave exactly as before.
"""
import os
import sys

if os.environ.get("JAX_PLATFORMS", "").partition(",")[0].strip() != "cpu":
    import importlib.util

    _here = os.path.dirname(os.path.abspath(__file__))
    for _p in sys.path:
        if not _p or os.path.abspath(_p) == _here:
            continue
        _cand = os.path.join(_p, "sitecustomize.py")
        if os.path.isfile(_cand):
            _spec = importlib.util.spec_from_file_location(
                "_chained_sitecustomize", _cand)
            _mod = importlib.util.module_from_spec(_spec)
            _spec.loader.exec_module(_mod)
            break
