"""SPMD data-plane tests on the 8-device virtual CPU mesh (SURVEY §4:
the JAX analogue of the reference's multi-process localhost testing)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.common.jax_compat import shard_map

from horovod_tpu.ops.adasum import adasum_reference
from horovod_tpu.parallel import (GradSyncConfig, MeshSpec, adasum_allreduce,
                                  build_grad_sync, build_mesh,
                                  device_collective, ShardingRules,
                                  shard_params, sync_gradients)
from horovod_tpu.parallel import collectives as coll


@pytest.fixture(scope="module")
def mesh8():
    return build_mesh(dp=8)


@pytest.fixture(scope="module")
def mesh_dp_tp():
    return build_mesh(dp=4, tp=2)


def stacked(n, shape, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return rng.randn(n, *shape).astype(dtype)


class TestMeshBuild:
    def test_resolve_infers_dp(self):
        assert MeshSpec(tp=2).resolve(8)["dp"] == 4

    def test_bad_divisibility(self):
        with pytest.raises(ValueError):
            MeshSpec(tp=3).resolve(8)

    def test_axis_names(self, mesh_dp_tp):
        assert mesh_dp_tp.shape["dp"] == 4
        assert mesh_dp_tp.shape["tp"] == 2
        assert mesh_dp_tp.shape["pp"] == 1


class TestCollectives:
    def test_psum(self, mesh8):
        x = stacked(8, (4, 3))
        fn = device_collective(lambda v: coll.allreduce(v, "dp", "sum"),
                               mesh8, "dp")
        out = np.asarray(fn(x))
        expect = x.sum(axis=0, keepdims=True).repeat(8, axis=0)
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_pmean(self, mesh8):
        x = stacked(8, (5,))
        fn = device_collective(lambda v: coll.allreduce(v, "dp", "average"),
                               mesh8, "dp")
        np.testing.assert_allclose(np.asarray(fn(x))[0], x.mean(0),
                                   rtol=1e-5)

    def test_broadcast(self, mesh8):
        x = stacked(8, (6,))
        fn = device_collective(lambda v: coll.broadcast(v, "dp", root=3),
                               mesh8, "dp")
        out = np.asarray(fn(x))
        for r in range(8):
            np.testing.assert_allclose(out[r], x[3], rtol=1e-6)

    def test_allgather_reduce_scatter_roundtrip(self, mesh8):
        x = stacked(8, (4,))
        fn = device_collective(
            lambda v: coll.reduce_scatter(coll.allgather(v, "dp"), "dp"),
            mesh8, "dp")
        out = np.asarray(fn(x))
        # allgather stacks all shards; reduce_scatter sums and re-shards:
        # each rank ends with 8 * its own shard
        np.testing.assert_allclose(out, 8 * x, rtol=1e-5)

    def test_alltoall(self, mesh8):
        x = stacked(8, (8, 2))
        # shard_map keeps the stacked leading dim (size 1 per rank), so the
        # exchange axis of the local block is axis 1.
        fn = device_collective(
            lambda v: coll.alltoall(v, "dp", split_axis=1, concat_axis=1),
            mesh8, "dp")
        out = np.asarray(fn(x))
        # row j of rank i's output == row i of rank j's input
        for i in range(8):
            for j in range(8):
                np.testing.assert_allclose(out[i, j], x[j, i], rtol=1e-6)


class TestAdasum:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_reference_tree(self, n):
        mesh = build_mesh(dp=n, devices=jax.devices()[:n])
        x = stacked(n, (33,), seed=n)
        fn = device_collective(lambda v: adasum_allreduce(v, "dp"),
                               mesh, "dp")
        out = np.asarray(fn(x))
        expect = adasum_reference(list(x))
        for r in range(n):
            np.testing.assert_allclose(out[r], expect, rtol=1e-4)

    def test_identical_inputs_average(self, mesh8):
        # Adasum of identical vectors = the vector itself (a·b = ‖a‖²
        # → coefs 1/2) — the scale-insensitivity property.
        v = np.tile(stacked(1, (16,), seed=3), (8, 1))
        fn = device_collective(lambda t: adasum_allreduce(t, "dp"),
                               mesh8, "dp")
        np.testing.assert_allclose(np.asarray(fn(v))[0], v[0], rtol=1e-4)

    def test_non_pow2_raises(self):
        mesh = build_mesh(dp=3, devices=jax.devices()[:3])
        x = stacked(3, (8,))
        fn = device_collective(lambda v: adasum_allreduce(v, "dp"),
                               mesh, "dp")
        with pytest.raises(ValueError, match="power-of-2"):
            fn(x)


class TestGradSync:
    def _tree(self, n, seed=0):
        rng = np.random.RandomState(seed)
        return {
            "dense": {"kernel": rng.randn(n, 8, 4).astype(np.float32),
                      "bias": rng.randn(n, 4).astype(np.float32)},
            "head": {"kernel": rng.randn(n, 4, 2).astype(np.float32)},
        }

    def test_average_matches_manual(self, mesh8):
        tree = self._tree(8)
        fn = build_grad_sync(mesh8, GradSyncConfig(op="average"))
        out = fn(tree)
        for path in [("dense", "kernel"), ("dense", "bias"),
                     ("head", "kernel")]:
            got = np.asarray(out[path[0]][path[1]])
            want = tree[path[0]][path[1]].mean(0, keepdims=True)
            np.testing.assert_allclose(got, np.repeat(want, 8, 0), rtol=1e-5)

    def test_fusion_small_buckets_same_result(self, mesh8):
        tree = self._tree(8, seed=1)
        big = build_grad_sync(mesh8, GradSyncConfig(op="sum"))
        tiny = build_grad_sync(
            mesh8, GradSyncConfig(op="sum", fusion_threshold_bytes=16))
        a, b = big(tree), tiny(tree)
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5), a, b)

    def test_fp16_compression_reduces_in_fp16(self, mesh8):
        tree = {"w": stacked(8, (64,), seed=2)}
        fn = build_grad_sync(
            mesh8, GradSyncConfig(op="average", compression="fp16"))
        out = np.asarray(fn(tree)["w"])
        expect = np.mean(tree["w"].astype(np.float16), axis=0,
                         dtype=np.float32)
        np.testing.assert_allclose(out[0], expect, atol=2e-3)
        assert out.dtype == np.float32   # decompressed back

    def test_adasum_tree(self, mesh8):
        tree = {"w": stacked(8, (17,), seed=5)}
        fn = build_grad_sync(mesh8, GradSyncConfig(op="adasum"))
        out = np.asarray(fn(tree)["w"])
        expect = adasum_reference(list(tree["w"]))
        np.testing.assert_allclose(out[0], expect, rtol=1e-4)

    def test_mixed_dtype_tree(self, mesh8):
        tree = {"f32": stacked(8, (10,), seed=6),
                "bf16": stacked(8, (12,), seed=7).astype(jnp.bfloat16)}
        fn = build_grad_sync(mesh8, GradSyncConfig(op="sum"))
        out = fn(tree)
        np.testing.assert_allclose(np.asarray(out["f32"])[0],
                                   tree["f32"].sum(0), rtol=1e-5)
        assert out["bf16"].dtype == jnp.bfloat16


class TestSharding:
    def test_rules_place_params(self, mesh_dp_tp):
        params = {"attn": {"kernel": np.zeros((8, 16), np.float32)},
                  "bias": np.zeros((16,), np.float32)}
        rules = ShardingRules([(r"attn.*kernel", P(None, "tp"))])
        placed = shard_params(params, mesh_dp_tp, rules)
        kspec = placed["attn"]["kernel"].sharding.spec
        assert tuple(kspec) == (None, "tp")
        bspec = placed["bias"].sharding.spec
        assert tuple(bspec) == ()

    def test_rule_rank_mismatch_falls_through(self, mesh_dp_tp):
        rules = ShardingRules([(r".*", P(None, "tp"))])
        params = {"bias": np.zeros((4,), np.float32)}
        placed = shard_params(params, mesh_dp_tp, rules)
        assert tuple(placed["bias"].sharding.spec) == ()

    def test_overlapping_rules_first_match_wins(self):
        rules = ShardingRules([
            (r"attn.*kernel", P(None, "tp")),
            (r".*kernel", P("dp", None)),
        ])
        assert tuple(rules.spec_for("attn/q/kernel")) == (None, "tp")
        assert tuple(rules.spec_for("mlp/up/kernel")) == ("dp", None)

    def test_patterns_are_searched_not_anchored(self):
        # search(), not fullmatch(): a mid-path token matches, and an
        # author who wants anchoring spells ^...$ explicitly.
        rules = ShardingRules([(r"mlp/up", P(None, "tp")),
                               (r"^bias$", P("dp"))])
        assert tuple(rules.spec_for("layer0/mlp/up/kernel")) \
            == (None, "tp")
        assert tuple(rules.spec_for("bias")) == ("dp",)
        assert tuple(rules.spec_for("layer0/bias")) == ()

    def test_empty_spec_rule_blocks_later_rules(self):
        # P() is a legitimate "explicitly replicated" terminal rule —
        # it wins for its paths and never rank-skips (len 0 fits any
        # leaf).
        rules = ShardingRules([(r"norm", P()),
                               (r".*", P("dp"))])
        leaf = np.zeros((4,), np.float32)
        assert tuple(rules.spec_for("norm/scale", leaf)) == ()
        assert tuple(rules.spec_for("w", leaf)) == ("dp",)

    def test_validate_flags_unknown_axis(self, mesh_dp_tp):
        rules = ShardingRules([(r".*kernel", P(None, "model"))])
        params = {"attn": {"kernel": np.zeros((2, 2), np.float32)}}
        problems = rules.validate(mesh_dp_tp, params)
        assert any("HVD802" in p and "'model'" in p for p in problems)

    def test_validate_flags_dead_rule(self, mesh_dp_tp):
        rules = ShardingRules([(r"decoder.*kernel", P(None, "tp"))])
        params = {"attn": {"kernel": np.zeros((2, 2), np.float32)}}
        problems = rules.validate(mesh_dp_tp, params)
        assert any("HVD801 dead rule" in p and "decoder" in p
                   for p in problems)

    def test_validate_flags_uncovered_sibling(self, mesh_dp_tp):
        # wq is sharded; wk under the same parent falls through to
        # replicated — the classic forgotten-sibling hole.
        rules = ShardingRules([(r"attn/wq", P(None, "tp"))])
        params = {"attn": {"wq": np.zeros((2, 2), np.float32),
                           "wk": np.zeros((2, 2), np.float32)}}
        problems = rules.validate(mesh_dp_tp, params)
        assert any("HVD801 uncovered path" in p and "attn/wk" in p
                   for p in problems)

    def test_validate_clean_table_returns_empty(self, mesh_dp_tp):
        rules = ShardingRules([(r"attn/w[qk]", P(None, "tp"))])
        params = {"attn": {"wq": np.zeros((2, 2), np.float32),
                           "wk": np.zeros((2, 2), np.float32)}}
        assert rules.validate(mesh_dp_tp, params) == []


def test_hierarchical_allreduce_matches_flat():
    """Explicit reduce_scatter->cross->all_gather equals the flat psum
    (reference: NCCLHierarchicalAllreduce semantics)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.parallel import MeshSpec, build_mesh
    from horovod_tpu.parallel.grad_sync import (GradSyncConfig,
                                                build_grad_sync)

    mesh = build_mesh(MeshSpec(dp=2, fsdp=4))
    # 8 stacked per-rank gradients; sizes chosen to force local padding
    # (13 not divisible by local_size 4).
    grads = {"w": jnp.arange(8 * 13, dtype=jnp.float32).reshape(8, 13),
             "b": jnp.ones((8, 4), jnp.float32)}
    flat_fn = build_grad_sync(mesh, GradSyncConfig(
        axes=("dp", "fsdp"), op="average"))
    hier_fn = build_grad_sync(mesh, GradSyncConfig(
        axes=("dp", "fsdp"), op="average", hierarchical=True))
    a = flat_fn(grads)
    b = hier_fn(grads)
    for k in grads:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=1e-6)


def test_profiler_hooks(tmp_path):
    import horovod_tpu as hvd
    hvd.start_profiler(str(tmp_path))
    with hvd.profiler_annotation("step"):
        import jax.numpy as jnp
        (jnp.ones(8) * 2).block_until_ready()
    hvd.stop_profiler()
    import os
    assert any(os.scandir(str(tmp_path)))


# ---------------------------------------------------------------------------
# Pipeline parallelism (VERDICT r1 item 6: exactness vs unpipelined)
# ---------------------------------------------------------------------------
class TestPipeline:
    def _setup(self, n_stages=4, m=4, batch=8, dim=6):
        from horovod_tpu.parallel.pipeline import pipeline_apply

        rng = np.random.default_rng(0)
        # One dense stage per pp rank: h -> tanh(h @ W + b)
        Ws = rng.standard_normal((n_stages, dim, dim)).astype(np.float32) * 0.3
        bs = rng.standard_normal((n_stages, dim)).astype(np.float32) * 0.1
        x = rng.standard_normal((batch, dim)).astype(np.float32)

        def stage_fn(params, h):
            W, b = params
            return jnp.tanh(h @ W + b)

        def serial(Ws, bs, x):
            h = x
            for i in range(n_stages):
                h = stage_fn((Ws[i], bs[i]), h)
            return h

        mesh = build_mesh(MeshSpec(pp=n_stages))  # dp absorbs the rest

        def piped(Ws, bs, x):
            return shard_map(
                lambda W, b, xx: pipeline_apply(
                    stage_fn, (W[0], b[0]), xx, axis="pp",
                    num_microbatches=m, axis_size=n_stages),
                mesh=mesh, in_specs=(P("pp"), P("pp"), P()),
                out_specs=P(), axis_names=frozenset({"pp"}),
                check_vma=False)(Ws, bs, x)

        return Ws, bs, x, serial, piped

    def test_forward_matches_serial(self):
        Ws, bs, x, serial, piped = self._setup()
        np.testing.assert_allclose(jax.jit(piped)(Ws, bs, x),
                                   serial(Ws, bs, x), rtol=1e-5, atol=1e-6)

    def test_gradients_match_serial(self):
        Ws, bs, x, serial, piped = self._setup()

        def loss_p(Ws, bs):
            return jnp.sum(piped(Ws, bs, x) ** 2)

        def loss_s(Ws, bs):
            return jnp.sum(serial(Ws, bs, x) ** 2)

        gp = jax.jit(jax.grad(loss_p, argnums=(0, 1)))(Ws, bs)
        gs = jax.grad(loss_s, argnums=(0, 1))(Ws, bs)
        for a, b in zip(gp, gs):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_uneven_microbatches(self):
        # m != n_stages exercises fill/drain bookkeeping.
        Ws, bs, x, serial, piped = self._setup(n_stages=2, m=4, batch=8)
        np.testing.assert_allclose(jax.jit(piped)(Ws, bs, x),
                                   serial(Ws, bs, x), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Mixture-of-Experts (VERDICT r1 item 6: ep all_to_all path + capacity)
# ---------------------------------------------------------------------------
class TestMoE:
    def test_expert_parallel_matches_dense(self):
        """With capacity high enough that nothing drops, the two
        all_to_all expert-parallel path must equal the dense einsum."""
        from horovod_tpu.models.moe import MoEMLP

        mesh = build_mesh(MeshSpec(ep=4))  # dp absorbs the rest
        b, t, d, e = 8, 4, 6, 4
        rng = np.random.default_rng(1)
        x = rng.standard_normal((b, t, d)).astype(np.float32)

        dense_moe = MoEMLP(num_experts=e, d_ff=16, capacity_factor=float(e),
                           ep_mesh=None)
        ep_moe = MoEMLP(num_experts=e, d_ff=16, capacity_factor=float(e),
                        ep_mesh=mesh, ep_axis="ep")
        variables = dense_moe.init(jax.random.key(0), jnp.asarray(x))
        out_dense = dense_moe.apply(variables, jnp.asarray(x))
        out_ep = jax.jit(lambda v, xx: ep_moe.apply(v, xx))(
            variables, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out_ep),
                                   np.asarray(out_dense),
                                   rtol=1e-4, atol=1e-5)

    def test_expert_parallel_gradients_match_dense(self):
        from horovod_tpu.models.moe import MoEMLP

        mesh = build_mesh(MeshSpec(ep=4))  # dp absorbs the rest
        b, t, d, e = 8, 4, 6, 4
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((b, t, d)).astype(np.float32))
        dense_moe = MoEMLP(num_experts=e, d_ff=16, capacity_factor=float(e),
                           ep_mesh=None)
        ep_moe = MoEMLP(num_experts=e, d_ff=16, capacity_factor=float(e),
                        ep_mesh=mesh, ep_axis="ep")
        variables = dense_moe.init(jax.random.key(0), x)

        gd = jax.grad(lambda v: jnp.sum(dense_moe.apply(v, x) ** 2))(
            variables)
        ge = jax.jit(jax.grad(
            lambda v: jnp.sum(ep_moe.apply(v, x) ** 2)))(variables)
        flat_d = jax.tree_util.tree_leaves_with_path(gd)
        flat_e = jax.tree_util.tree_leaves_with_path(ge)
        for (pd, ld), (pe, le) in zip(flat_d, flat_e):
            assert pd == pe
            np.testing.assert_allclose(np.asarray(le), np.asarray(ld),
                                       rtol=1e-3, atol=1e-4,
                                       err_msg=str(pd))

    def test_capacity_drops_tokens(self):
        """Switch semantics: tokens beyond an expert's capacity produce
        zero output (dropped), not an error."""
        from horovod_tpu.models.moe import _capacity, _dispatch_combine

        n, e = 8, 2
        # All tokens prefer expert 0.
        logits = np.full((n, e), -10.0, dtype=np.float32)
        logits[:, 0] = 10.0
        cap = _capacity(n, e, factor=0.5)   # 2 slots for expert 0
        dispatch, combine = _dispatch_combine(jnp.asarray(logits), cap)
        kept = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        assert kept.sum() == cap            # only `cap` tokens kept
        np.testing.assert_array_equal(kept[:cap], np.ones(cap))
        np.testing.assert_array_equal(kept[cap:], np.zeros(n - cap))

    def test_moe_transformer_trains_over_ep(self):
        """TransformerLM(moe_experts=N) under the GSPMD Trainer on a
        dp x ep mesh: one full train step, finite loss, step advances."""
        import dataclasses

        import optax

        from horovod_tpu import models, training

        mesh = build_mesh(MeshSpec(dp=2, ep=4))
        cfg = dataclasses.replace(
            models.gpt_tiny(dtype=jnp.float32), num_layers=2,
            moe_experts=4, mesh=mesh)
        lm = models.TransformerLM(cfg)
        trainer = training.Trainer(
            lm, optax.adamw(1e-3), mesh,
            sync=GradSyncConfig(axes=(), op="average"),
            batch_spec=P(("dp", "ep")))
        batch = training.synthetic_text_batch(8, seq_len=16,
                                              vocab_size=cfg.vocab_size)
        state = trainer.init(jax.random.key(0), batch)
        state, metrics = trainer.step(state, batch)
        assert int(state.step) == 1
        assert np.isfinite(float(metrics["loss"]))


class TestKvBarrier:
    """kv_barrier protocol (parallel/multihost.py): rendezvous-KV barrier
    with a per-world sequence — the non-collective alignment primitive
    the compile→barrier→dispatch pattern relies on."""

    def _fake_world(self, monkeypatch, rank, size, store):
        from horovod_tpu.parallel import multihost

        class FakeKV:
            def put(self, scope, key, value):
                store[(scope, key)] = value

            def wait(self, scope, key, timeout=5.0):
                import time
                end = time.time() + timeout
                while (scope, key) not in store:
                    if time.time() > end:
                        raise TimeoutError(key)
                    time.sleep(0.01)
                return store[(scope, key)]

        monkeypatch.setattr(multihost, "_initialized_here", True)
        monkeypatch.setattr(multihost, "_world",
                            (rank, size, FakeKV(), "ep0"))
        return multihost

    def test_barrier_waits_for_every_rank(self, monkeypatch):
        import threading

        store: dict = {}
        mh = self._fake_world(monkeypatch, 0, 2, store)
        monkeypatch.setattr(mh, "_barrier_seq", 0)
        done = threading.Event()

        def rank0():
            mh.kv_barrier("t", timeout=5.0)
            done.set()

        t = threading.Thread(target=rank0, daemon=True)
        t.start()
        # Rank 0 has published its key but must still be blocked on
        # rank 1's.
        assert not done.wait(0.3)
        assert ("barrier", "ep0:t:1:0") in store
        store[("barrier", "ep0:t:1:1")] = b"1"   # rank 1 arrives
        assert done.wait(5.0)
        t.join(5.0)

    def test_sequence_advances_per_call(self, monkeypatch):
        store: dict = {}
        mh = self._fake_world(monkeypatch, 0, 2, store)
        monkeypatch.setattr(mh, "_barrier_seq", 0)
        store[("barrier", "ep0:a:1:1")] = b"1"
        store[("barrier", "ep0:b:2:1")] = b"1"
        mh.kv_barrier("a", timeout=2.0)
        mh.kv_barrier("b", timeout=2.0)
        assert ("barrier", "ep0:a:1:0") in store
        assert ("barrier", "ep0:b:2:0") in store

    def test_noop_outside_world(self, monkeypatch):
        from horovod_tpu.parallel import multihost
        monkeypatch.setattr(multihost, "_initialized_here", False)
        multihost.kv_barrier("t", timeout=0.1)   # must not raise
