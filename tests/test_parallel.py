"""SPMD data-plane tests on the 8-device virtual CPU mesh (SURVEY §4:
the JAX analogue of the reference's multi-process localhost testing)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from jax import shard_map

from horovod_tpu.ops.adasum import adasum_reference
from horovod_tpu.parallel import (GradSyncConfig, MeshSpec, adasum_allreduce,
                                  build_grad_sync, build_mesh,
                                  device_collective, ShardingRules,
                                  shard_params, sync_gradients)
from horovod_tpu.parallel import collectives as coll


@pytest.fixture(scope="module")
def mesh8():
    return build_mesh(dp=8)


@pytest.fixture(scope="module")
def mesh_dp_tp():
    return build_mesh(dp=4, tp=2)


def stacked(n, shape, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return rng.randn(n, *shape).astype(dtype)


class TestMeshBuild:
    def test_resolve_infers_dp(self):
        assert MeshSpec(tp=2).resolve(8)["dp"] == 4

    def test_bad_divisibility(self):
        with pytest.raises(ValueError):
            MeshSpec(tp=3).resolve(8)

    def test_axis_names(self, mesh_dp_tp):
        assert mesh_dp_tp.shape["dp"] == 4
        assert mesh_dp_tp.shape["tp"] == 2
        assert mesh_dp_tp.shape["pp"] == 1


class TestCollectives:
    def test_psum(self, mesh8):
        x = stacked(8, (4, 3))
        fn = device_collective(lambda v: coll.allreduce(v, "dp", "sum"),
                               mesh8, "dp")
        out = np.asarray(fn(x))
        expect = x.sum(axis=0, keepdims=True).repeat(8, axis=0)
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_pmean(self, mesh8):
        x = stacked(8, (5,))
        fn = device_collective(lambda v: coll.allreduce(v, "dp", "average"),
                               mesh8, "dp")
        np.testing.assert_allclose(np.asarray(fn(x))[0], x.mean(0),
                                   rtol=1e-5)

    def test_broadcast(self, mesh8):
        x = stacked(8, (6,))
        fn = device_collective(lambda v: coll.broadcast(v, "dp", root=3),
                               mesh8, "dp")
        out = np.asarray(fn(x))
        for r in range(8):
            np.testing.assert_allclose(out[r], x[3], rtol=1e-6)

    def test_allgather_reduce_scatter_roundtrip(self, mesh8):
        x = stacked(8, (4,))
        fn = device_collective(
            lambda v: coll.reduce_scatter(coll.allgather(v, "dp"), "dp"),
            mesh8, "dp")
        out = np.asarray(fn(x))
        # allgather stacks all shards; reduce_scatter sums and re-shards:
        # each rank ends with 8 * its own shard
        np.testing.assert_allclose(out, 8 * x, rtol=1e-5)

    def test_alltoall(self, mesh8):
        x = stacked(8, (8, 2))
        # shard_map keeps the stacked leading dim (size 1 per rank), so the
        # exchange axis of the local block is axis 1.
        fn = device_collective(
            lambda v: coll.alltoall(v, "dp", split_axis=1, concat_axis=1),
            mesh8, "dp")
        out = np.asarray(fn(x))
        # row j of rank i's output == row i of rank j's input
        for i in range(8):
            for j in range(8):
                np.testing.assert_allclose(out[i, j], x[j, i], rtol=1e-6)


class TestAdasum:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_reference_tree(self, n):
        mesh = build_mesh(dp=n, devices=jax.devices()[:n])
        x = stacked(n, (33,), seed=n)
        fn = device_collective(lambda v: adasum_allreduce(v, "dp"),
                               mesh, "dp")
        out = np.asarray(fn(x))
        expect = adasum_reference(list(x))
        for r in range(n):
            np.testing.assert_allclose(out[r], expect, rtol=1e-4)

    def test_identical_inputs_average(self, mesh8):
        # Adasum of identical vectors = the vector itself (a·b = ‖a‖²
        # → coefs 1/2) — the scale-insensitivity property.
        v = np.tile(stacked(1, (16,), seed=3), (8, 1))
        fn = device_collective(lambda t: adasum_allreduce(t, "dp"),
                               mesh8, "dp")
        np.testing.assert_allclose(np.asarray(fn(v))[0], v[0], rtol=1e-4)

    def test_non_pow2_raises(self):
        mesh = build_mesh(dp=3, devices=jax.devices()[:3])
        x = stacked(3, (8,))
        fn = device_collective(lambda v: adasum_allreduce(v, "dp"),
                               mesh, "dp")
        with pytest.raises(ValueError, match="power-of-2"):
            fn(x)


class TestGradSync:
    def _tree(self, n, seed=0):
        rng = np.random.RandomState(seed)
        return {
            "dense": {"kernel": rng.randn(n, 8, 4).astype(np.float32),
                      "bias": rng.randn(n, 4).astype(np.float32)},
            "head": {"kernel": rng.randn(n, 4, 2).astype(np.float32)},
        }

    def test_average_matches_manual(self, mesh8):
        tree = self._tree(8)
        fn = build_grad_sync(mesh8, GradSyncConfig(op="average"))
        out = fn(tree)
        for path in [("dense", "kernel"), ("dense", "bias"),
                     ("head", "kernel")]:
            got = np.asarray(out[path[0]][path[1]])
            want = tree[path[0]][path[1]].mean(0, keepdims=True)
            np.testing.assert_allclose(got, np.repeat(want, 8, 0), rtol=1e-5)

    def test_fusion_small_buckets_same_result(self, mesh8):
        tree = self._tree(8, seed=1)
        big = build_grad_sync(mesh8, GradSyncConfig(op="sum"))
        tiny = build_grad_sync(
            mesh8, GradSyncConfig(op="sum", fusion_threshold_bytes=16))
        a, b = big(tree), tiny(tree)
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5), a, b)

    def test_fp16_compression_reduces_in_fp16(self, mesh8):
        tree = {"w": stacked(8, (64,), seed=2)}
        fn = build_grad_sync(
            mesh8, GradSyncConfig(op="average", compression="fp16"))
        out = np.asarray(fn(tree)["w"])
        expect = np.mean(tree["w"].astype(np.float16), axis=0,
                         dtype=np.float32)
        np.testing.assert_allclose(out[0], expect, atol=2e-3)
        assert out.dtype == np.float32   # decompressed back

    def test_adasum_tree(self, mesh8):
        tree = {"w": stacked(8, (17,), seed=5)}
        fn = build_grad_sync(mesh8, GradSyncConfig(op="adasum"))
        out = np.asarray(fn(tree)["w"])
        expect = adasum_reference(list(tree["w"]))
        np.testing.assert_allclose(out[0], expect, rtol=1e-4)

    def test_mixed_dtype_tree(self, mesh8):
        tree = {"f32": stacked(8, (10,), seed=6),
                "bf16": stacked(8, (12,), seed=7).astype(jnp.bfloat16)}
        fn = build_grad_sync(mesh8, GradSyncConfig(op="sum"))
        out = fn(tree)
        np.testing.assert_allclose(np.asarray(out["f32"])[0],
                                   tree["f32"].sum(0), rtol=1e-5)
        assert out["bf16"].dtype == jnp.bfloat16


class TestSharding:
    def test_rules_place_params(self, mesh_dp_tp):
        params = {"attn": {"kernel": np.zeros((8, 16), np.float32)},
                  "bias": np.zeros((16,), np.float32)}
        rules = ShardingRules([(r"attn.*kernel", P(None, "tp"))])
        placed = shard_params(params, mesh_dp_tp, rules)
        kspec = placed["attn"]["kernel"].sharding.spec
        assert tuple(kspec) == (None, "tp")
        bspec = placed["bias"].sharding.spec
        assert tuple(bspec) == ()

    def test_rule_rank_mismatch_falls_through(self, mesh_dp_tp):
        rules = ShardingRules([(r".*", P(None, "tp"))])
        params = {"bias": np.zeros((4,), np.float32)}
        placed = shard_params(params, mesh_dp_tp, rules)
        assert tuple(placed["bias"].sharding.spec) == ()


def test_hierarchical_allreduce_matches_flat():
    """Explicit reduce_scatter->cross->all_gather equals the flat psum
    (reference: NCCLHierarchicalAllreduce semantics)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.parallel import MeshSpec, build_mesh
    from horovod_tpu.parallel.grad_sync import (GradSyncConfig,
                                                build_grad_sync)

    mesh = build_mesh(MeshSpec(dp=2, fsdp=4))
    # 8 stacked per-rank gradients; sizes chosen to force local padding
    # (13 not divisible by local_size 4).
    grads = {"w": jnp.arange(8 * 13, dtype=jnp.float32).reshape(8, 13),
             "b": jnp.ones((8, 4), jnp.float32)}
    flat_fn = build_grad_sync(mesh, GradSyncConfig(
        axes=("dp", "fsdp"), op="average"))
    hier_fn = build_grad_sync(mesh, GradSyncConfig(
        axes=("dp", "fsdp"), op="average", hierarchical=True))
    a = flat_fn(grads)
    b = hier_fn(grads)
    for k in grads:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=1e-6)


def test_profiler_hooks(tmp_path):
    import horovod_tpu as hvd
    hvd.start_profiler(str(tmp_path))
    with hvd.profiler_annotation("step"):
        import jax.numpy as jnp
        (jnp.ones(8) * 2).block_until_ready()
    hvd.stop_profiler()
    import os
    assert any(os.scandir(str(tmp_path)))
