"""Transformer LM family: attention-impl equivalence and SPMD training
over dp x sp meshes (long-context path end to end)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import training
from horovod_tpu.models.transformer import TransformerLM, gpt_tiny
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.key(1), (4, 32), 0, 256)


@pytest.fixture(scope="module")
def dense_params(tokens):
    return TransformerLM(gpt_tiny(dtype=jnp.float32)).init(
        jax.random.key(0), tokens)


class TestAttentionImpls:
    def test_ring_matches_dense(self, tokens, dense_params):
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        ref = TransformerLM(gpt_tiny(dtype=jnp.float32)).apply(
            dense_params, tokens)
        out = TransformerLM(
            gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh,
                     batch_spec="dp")).apply(dense_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)

    def test_ulysses_matches_dense(self, tokens, dense_params):
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        ref = TransformerLM(gpt_tiny(dtype=jnp.float32)).apply(
            dense_params, tokens)
        out = TransformerLM(
            gpt_tiny(dtype=jnp.float32, attention="ulysses", mesh=mesh,
                     batch_spec="dp")).apply(dense_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)

    def test_flash_matches_dense(self, tokens, dense_params):
        ref = TransformerLM(gpt_tiny(dtype=jnp.float32)).apply(
            dense_params, tokens)
        out = TransformerLM(
            gpt_tiny(dtype=jnp.float32, attention="flash", block_q=16,
                     block_k=16, flash_interpret=True)).apply(
            dense_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)


class TestTraining:
    def _train(self, cfg, mesh, steps=3, axes=("dp",), batch_spec=None):
        model = TransformerLM(cfg)
        trainer = training.Trainer(
            model, optax.adamw(1e-3), mesh,
            sync=GradSyncConfig(axes=axes, op="average"),
            batch_spec=batch_spec)
        batch = training.synthetic_text_batch(8, seq_len=32, vocab_size=256)
        state = trainer.init(jax.random.key(0), batch)
        losses = []
        for _ in range(steps):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    def test_dense_lm_trains(self):
        mesh = build_mesh(MeshSpec(dp=8))
        losses = self._train(gpt_tiny(dtype=jnp.float32), mesh)
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_ring_sp_lm_trains(self):
        """Full SPMD train step with ring attention inside the jitted step:
        dp manual-mapped by the Trainer, sp manual-mapped by the model's
        nested shard_map."""
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        cfg = gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh)
        losses = self._train(cfg, mesh, axes=("dp", "sp"),
                             batch_spec=P("dp", "sp"))
        assert losses[-1] < losses[0]

    def test_ring_equals_dense_training(self):
        """One optimizer step with ring attention produces the same loss
        trajectory as dense attention."""
        mesh_d = build_mesh(MeshSpec(dp=8))
        mesh_r = build_mesh(MeshSpec(dp=2, sp=4))
        dense = self._train(gpt_tiny(dtype=jnp.float32), mesh_d, steps=2)
        ring = self._train(
            gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh_r),
            mesh_r, steps=2, axes=("dp", "sp"),
            batch_spec=P("dp", "sp"))
        np.testing.assert_allclose(ring, dense, rtol=2e-4)

    def test_remat_lm_trains(self):
        mesh = build_mesh(MeshSpec(dp=8))
        losses = self._train(gpt_tiny(dtype=jnp.float32, remat=True), mesh)
        assert losses[-1] < losses[0]

    def test_remat_dots_policy_matches_full(self):
        """remat_policy='dots' (save matmul outputs, recompute elementwise)
        must match full-block remat numerics — only the memory/FLOPs
        trade changes.  rtol matches test_ring_equals_dense_training:
        saved-vs-recomputed values may fuse/round differently, and adamw
        steps compound ulp-level differences."""
        mesh = build_mesh(MeshSpec(dp=8))
        dots = self._train(gpt_tiny(dtype=jnp.float32, remat=True,
                                    remat_policy="dots"), mesh)
        assert dots[-1] < dots[0]
        full = self._train(gpt_tiny(dtype=jnp.float32, remat=True), mesh)
        np.testing.assert_allclose(dots, full, rtol=2e-4)
