"""Transformer LM family: attention-impl equivalence and SPMD training
over dp x sp meshes (long-context path end to end)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import training
from horovod_tpu.models.transformer import TransformerLM, gpt_tiny
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import GradSyncConfig, MeshSpec, build_mesh


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.key(1), (4, 32), 0, 256)


@pytest.fixture(scope="module")
def dense_params(tokens):
    return TransformerLM(gpt_tiny(dtype=jnp.float32)).init(
        jax.random.key(0), tokens)


class TestAttentionImpls:
    def test_ring_matches_dense(self, tokens, dense_params):
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        ref = TransformerLM(gpt_tiny(dtype=jnp.float32)).apply(
            dense_params, tokens)
        out = TransformerLM(
            gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh,
                     batch_spec="dp")).apply(dense_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)

    def test_ulysses_matches_dense(self, tokens, dense_params):
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        ref = TransformerLM(gpt_tiny(dtype=jnp.float32)).apply(
            dense_params, tokens)
        out = TransformerLM(
            gpt_tiny(dtype=jnp.float32, attention="ulysses", mesh=mesh,
                     batch_spec="dp")).apply(dense_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)

    def test_flash_matches_dense(self, tokens, dense_params):
        ref = TransformerLM(gpt_tiny(dtype=jnp.float32)).apply(
            dense_params, tokens)
        out = TransformerLM(
            gpt_tiny(dtype=jnp.float32, attention="flash", block_q=16,
                     block_k=16, flash_interpret=True)).apply(
            dense_params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-4)


class TestTraining:
    def _train(self, cfg, mesh, steps=3, axes=("dp",), batch_spec=None):
        model = TransformerLM(cfg)
        trainer = training.Trainer(
            model, optax.adamw(1e-3), mesh,
            sync=GradSyncConfig(axes=axes, op="average"),
            batch_spec=batch_spec)
        batch = training.synthetic_text_batch(8, seq_len=32, vocab_size=256)
        state = trainer.init(jax.random.key(0), batch)
        losses = []
        for _ in range(steps):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    def test_dense_lm_trains(self):
        mesh = build_mesh(MeshSpec(dp=8))
        losses = self._train(gpt_tiny(dtype=jnp.float32), mesh)
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_ring_sp_lm_trains(self):
        """Full SPMD train step with ring attention inside the jitted step:
        dp manual-mapped by the Trainer, sp manual-mapped by the model's
        nested shard_map."""
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        cfg = gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh)
        losses = self._train(cfg, mesh, axes=("dp", "sp"),
                             batch_spec=P("dp", "sp"))
        assert losses[-1] < losses[0]

    def test_ring_equals_dense_training(self):
        """One optimizer step with ring attention produces the same loss
        trajectory as dense attention."""
        mesh_d = build_mesh(MeshSpec(dp=8))
        mesh_r = build_mesh(MeshSpec(dp=2, sp=4))
        dense = self._train(gpt_tiny(dtype=jnp.float32), mesh_d, steps=2)
        ring = self._train(
            gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh_r),
            mesh_r, steps=2, axes=("dp", "sp"),
            batch_spec=P("dp", "sp"))
        np.testing.assert_allclose(ring, dense, rtol=2e-4)

    def test_remat_lm_trains(self):
        mesh = build_mesh(MeshSpec(dp=8))
        losses = self._train(gpt_tiny(dtype=jnp.float32, remat=True), mesh)
        assert losses[-1] < losses[0]

    def test_remat_dots_policy_matches_full(self):
        """remat_policy='dots' (save matmul outputs, recompute elementwise)
        must match full-block remat numerics — only the memory/FLOPs
        trade changes.  rtol matches test_ring_equals_dense_training:
        saved-vs-recomputed values may fuse/round differently, and adamw
        steps compound ulp-level differences."""
        mesh = build_mesh(MeshSpec(dp=8))
        dots = self._train(gpt_tiny(dtype=jnp.float32, remat=True,
                                    remat_policy="dots"), mesh)
        assert dots[-1] < dots[0]
        full = self._train(gpt_tiny(dtype=jnp.float32, remat=True), mesh)
        np.testing.assert_allclose(dots, full, rtol=2e-4)


class TestIncrementalDecode:
    """KV-cache prefill/decode parity vs the full forward pass (ISSUE 9
    satellite: continuous batching must pay one token of compute per
    step without changing the math)."""

    def _models(self):
        import dataclasses

        from horovod_tpu.models import transformer as tfm
        cfg = gpt_tiny(dtype=jnp.float32, max_seq_len=64)
        return (TransformerLM(cfg),
                TransformerLM(dataclasses.replace(cfg, decode=True)))

    def test_prefill_then_decode_matches_full_forward(self):
        from horovod_tpu.models import transformer as tfm
        full_model, dmodel = self._models()
        toks = jax.random.randint(jax.random.key(3), (2, 12), 0, 256)
        variables = full_model.init(jax.random.key(0), toks)
        full = full_model.apply(variables, toks)          # [2,12,V]

        logits, cache = tfm.prefill(dmodel, variables, toks[:, :5])
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, :5]),
                                   atol=2e-3, rtol=2e-3)
        for i in range(5, 12):
            step, cache = tfm.decode_step(dmodel, variables, cache,
                                          toks[:, i:i + 1])
            np.testing.assert_allclose(np.asarray(step[:, 0]),
                                       np.asarray(full[:, i]),
                                       atol=2e-3, rtol=2e-3)

    def test_padded_prefill_uneven_depths(self):
        """Right-padded prompts of different lengths share one prefill
        call; each row then decodes from its own cache depth — the
        continuous-batching shape — and stays on the full-forward
        trajectory."""
        from horovod_tpu.models import transformer as tfm
        full_model, dmodel = self._models()
        toks = np.asarray(jax.random.randint(jax.random.key(5), (2, 8),
                                             0, 256))
        lens = np.array([3, 5], np.int32)
        padded = np.zeros((2, 8), np.int32)
        padded[0, :3] = toks[0, :3]
        padded[1, :5] = toks[1, :5]
        variables = full_model.init(jax.random.key(0),
                                    jnp.asarray(padded))
        logits, cache = tfm.prefill(dmodel, variables,
                                    jnp.asarray(padded), lengths=lens)
        full0 = full_model.apply(variables, jnp.asarray(toks[:1]))
        full1 = full_model.apply(variables, jnp.asarray(toks[1:]))
        np.testing.assert_allclose(np.asarray(logits[0, 2]),
                                   np.asarray(full0[0, 2]),
                                   atol=2e-3, rtol=2e-3)
        np.testing.assert_allclose(np.asarray(logits[1, 4]),
                                   np.asarray(full1[0, 4]),
                                   atol=2e-3, rtol=2e-3)
        for j in range(3):
            step = jnp.asarray(
                np.stack([toks[0, 3 + j], toks[1, 5 + j]])[:, None])
            lg, cache = tfm.decode_step(dmodel, variables, cache, step)
            np.testing.assert_allclose(np.asarray(lg[0, 0]),
                                       np.asarray(full0[0, 3 + j]),
                                       atol=2e-3, rtol=2e-3)
            np.testing.assert_allclose(np.asarray(lg[1, 0]),
                                       np.asarray(full1[0, 5 + j]),
                                       atol=2e-3, rtol=2e-3)

    def test_paged_decode_matches_dense_decode_uneven_depths(self):
        """ISSUE 14 parity: the paged block-pool decode path (scatter
        writes through block tables + table-indexed gather) must stay
        on the dense decode path's trajectory — same right-padded
        shared prefill, each row at its own depth."""
        import dataclasses

        from horovod_tpu.models import transformer as tfm
        cfg = gpt_tiny(dtype=jnp.float32, max_seq_len=64)
        full_model = TransformerLM(cfg)
        dmodel = TransformerLM(dataclasses.replace(cfg, decode=True))
        pmodel = TransformerLM(dataclasses.replace(
            cfg, decode=True, paged=True, kv_pool_blocks=16,
            kv_block_tokens=8))
        toks = jax.random.randint(jax.random.key(3), (2, 12), 0, 256)
        variables = full_model.init(jax.random.key(0), toks)

        lens = jnp.array([5, 9], jnp.int32)
        padded = np.asarray(toks).copy()
        padded[0, 5:] = 0
        padded[1, 9:] = 0
        dlogits, dcache = tfm.prefill(dmodel, variables,
                                      jnp.asarray(padded), lengths=lens)
        # Paged: disjoint block runs per row (8 tokens/block, 8 blocks
        # of table width = 64 positions = max_seq_len, so the gathered
        # attention length matches the dense path exactly).
        tables = jnp.array([[0, 1, 2, 3, 4, 5, 6, 7],
                            [8, 9, 10, 11, 12, 13, 14, 15]], jnp.int32)
        _, mut = pmodel.apply(variables, jnp.zeros((2, 1), jnp.int32),
                              block_tables=tables,
                              cursors=jnp.zeros(2, jnp.int32),
                              mutable=["cache"])
        from flax.core import unfreeze
        pcache = unfreeze(mut["cache"])
        plogits, pcache = tfm.paged_apply(
            pmodel, variables, pcache, jnp.asarray(padded), tables,
            jnp.zeros(2, jnp.int32), lengths=lens)
        np.testing.assert_allclose(np.asarray(plogits[0, :5]),
                                   np.asarray(dlogits[0, :5]),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(plogits[1, :9]),
                                   np.asarray(dlogits[1, :9]),
                                   atol=1e-5, rtol=1e-5)
        cur = np.array([5, 9], np.int32)
        for _ in range(3):
            step = jnp.asarray(np.stack([
                np.asarray(toks)[0, cur[0]],
                np.asarray(toks)[1, cur[1]]])[:, None])
            dl, dcache = tfm.decode_step(dmodel, variables, dcache, step)
            pl, pcache = tfm.paged_apply(pmodel, variables, pcache,
                                         step, tables,
                                         jnp.asarray(cur))
            np.testing.assert_allclose(np.asarray(pl), np.asarray(dl),
                                       atol=1e-5, rtol=1e-5)
            cur += 1

    def test_paged_cow_divergence_isolates_sequences(self):
        """Two rows share a prompt's physical blocks (the prefix-cache
        posture); before row 1 writes into the partial tail it gets a
        private copy (paged_copy_block — the tensor half of the pool's
        COW).  Both rows then decode DIFFERENT continuations and each
        must match its own dense-path trajectory: the copy isolates
        them, the shared full block stays intact."""
        import dataclasses

        from horovod_tpu.models import transformer as tfm
        cfg = gpt_tiny(dtype=jnp.float32, max_seq_len=64)
        full_model = TransformerLM(cfg)
        dmodel = TransformerLM(dataclasses.replace(cfg, decode=True))
        pmodel = TransformerLM(dataclasses.replace(
            cfg, decode=True, paged=True, kv_pool_blocks=16,
            kv_block_tokens=8))
        prompt = jax.random.randint(jax.random.key(7), (1, 12), 0, 256)
        both = jnp.concatenate([prompt, prompt])        # [2,12]
        variables = full_model.init(jax.random.key(0), both)

        # Dense reference: batch of two identical prompts, decoded with
        # diverging continuations.
        lens = jnp.array([12, 12], jnp.int32)
        _, dcache = tfm.prefill(dmodel, variables, both, lengths=lens)

        # Paged: prefill ONCE into blocks [0 (full), 1 (tail)], then
        # share them — row 0 keeps [0, 1], row 1 COWs the tail to
        # block 5 and keeps the full block shared.
        tables0 = jnp.array([[0, 1, 2, 3, 15, 15, 15, 15],
                             [0, 5, 6, 7, 15, 15, 15, 15]], jnp.int32)
        _, mut = pmodel.apply(variables, jnp.zeros((2, 1), jnp.int32),
                              block_tables=tables0,
                              cursors=jnp.zeros(2, jnp.int32),
                              mutable=["cache"])
        from flax.core import unfreeze
        pcache = unfreeze(mut["cache"])
        # Prefill only row 0's blocks (row 1 masked out via lengths=0).
        plogits, pcache = tfm.paged_apply(
            pmodel, variables, pcache, both,
            jnp.array([[0, 1, 2, 3, 15, 15, 15, 15]] * 2, jnp.int32),
            jnp.zeros(2, jnp.int32), lengths=jnp.array([12, 0]))
        # COW the partial tail (block 1 -> block 5) for row 1.
        pcache = tfm.paged_copy_block(pcache, 1, 5)
        cont = np.array([[3, 9, 4], [200, 17, 66]], np.int32)
        cur = np.array([12, 12], np.int32)
        for j in range(3):
            step = jnp.asarray(cont[:, j][:, None])
            dl, dcache = tfm.decode_step(dmodel, variables, dcache,
                                         step)
            pl, pcache = tfm.paged_apply(pmodel, variables, pcache,
                                         step, tables0,
                                         jnp.asarray(cur))
            np.testing.assert_allclose(np.asarray(pl), np.asarray(dl),
                                       atol=1e-5, rtol=1e-5)
            cur += 1

    def test_decode_rejects_sequence_parallel(self):
        import dataclasses

        from horovod_tpu.models import transformer as tfm
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        cfg = dataclasses.replace(
            gpt_tiny(dtype=jnp.float32, attention="ring", mesh=mesh,
                     batch_spec="dp"), decode=True)
        model = TransformerLM(cfg)
        toks = jnp.zeros((1, 4), jnp.int32)
        variables = TransformerLM(gpt_tiny(dtype=jnp.float32)).init(
            jax.random.key(0), toks)
        with pytest.raises(ValueError, match="decode"):
            model.apply(variables, toks, mutable=["cache"])
