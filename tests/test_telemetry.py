"""telemetry/ unit tests (ISSUE 4): registry semantics + thread safety,
Prometheus exposition golden file, straggler aggregation, exporter HTTP
endpoint, JSON dump + report CLI, wire snapshot round-trip, timeline
counter events, and the HOROVOD_METRICS=off no-op contract."""
from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from horovod_tpu.common.message import RequestList
from horovod_tpu.common.timeline import Timeline
from horovod_tpu.telemetry import (NULL_METRIC, NULL_REGISTRY,
                                   MetricsExporter, MetricsRegistry,
                                   StragglerAggregator, dump_json,
                                   resolve_dump_path)
from horovod_tpu.telemetry.registry import bucket_upper_bound
from horovod_tpu.telemetry.report import (summarize_dump, summarize_file,
                                          summarize_timeline)

import os

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "telemetry")


# --- registry ---------------------------------------------------------------
def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry(0)
    c = reg.counter("c_total", "help")
    c.inc()
    c.inc(4)
    assert c.value == 5
    # Same (name, labels) -> same object; different labels -> different.
    assert reg.counter("c_total") is c
    assert reg.counter("c_total", labels={"x": "1"}) is not c

    g = reg.gauge("g")
    g.set(2.5)
    g.set(1.0)
    assert g.value == 1.0

    h = reg.histogram("h_ms")
    for v in (0.5, 1.5, 3.0, 12.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(17.0)
    assert h.mean == pytest.approx(4.25)
    # log2 buckets: p50 falls in the <=2 bucket, p99 in the <=16 bucket.
    assert h.percentile(50) == 2.0
    assert h.percentile(99) == 16.0
    bounds = [b for b, _ in h.nonzero_buckets()]
    assert bounds == [0.5, 2.0, 4.0, 16.0]


def test_histogram_quantile_interpolates_and_clamps():
    """ISSUE 9 satellite: quantile(q) interpolates geometrically inside
    the log2 bucket (serving SLO p50/p99/p999 and training step times
    share this one path) and clamps to the observed min/max, unlike the
    bucket-bound percentile()."""
    reg = MetricsRegistry(0)
    h = reg.histogram("q_ms")
    for v in (0.5, 1.5, 3.0, 12.0):
        h.observe(v)
    assert h.quantile(0.5) == pytest.approx(2.0)
    # p99 interpolates to ~15.6 inside the (8, 16] bucket, then clamps
    # to the observed max of 12 — percentile() would report 16.
    assert h.quantile(0.99) == pytest.approx(12.0)
    assert h.percentile(99) == 16.0
    assert h.quantile(0.0) == pytest.approx(0.5)   # clamped to min
    assert h.quantile(1.0) == pytest.approx(12.0)
    # Single-bucket histogram: every quantile stays inside the bucket.
    h2 = reg.histogram("one_bucket")
    for _ in range(100):
        h2.observe(3.0)
    assert h2.quantile(0.5) == pytest.approx(3.0)
    assert h2.quantile(0.999) == pytest.approx(3.0)
    # Empty histogram: 0.0, and the snapshot carries quantile p50/p99.
    assert reg.histogram("empty").quantile(0.5) == 0.0
    snap = {m["name"]: m for m in reg.snapshot()["metrics"]}
    assert snap["q_ms"]["p50"] == pytest.approx(2.0)
    assert snap["q_ms"]["p99"] == pytest.approx(12.0)


def test_histogram_quantile_edge_cases():
    """ISSUE 19 satellite: the degenerate shapes the busbw ledger folds
    over — a single observation, everything in one bucket, and an empty
    histogram — must all produce sane quantiles (the PERF.json p50/p99
    columns are built from exactly these)."""
    reg = MetricsRegistry(0)
    # Single observation: every quantile is that value (min == max
    # clamps both ends of the interpolation).
    h = reg.histogram("single")
    h.observe(7.25)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == pytest.approx(7.25)
    # All observations in one bucket: interpolation cannot escape it.
    h2 = reg.histogram("uniform")
    for _ in range(50):
        h2.observe(3.0)
    assert h2.quantile(0.01) == pytest.approx(3.0)
    assert h2.quantile(0.999) == pytest.approx(3.0)
    # Empty: quantiles are 0.0 at every q, no division by zero.
    h3 = reg.histogram("void")
    for q in (0.0, 0.5, 1.0):
        assert h3.quantile(q) == 0.0


def test_histogram_bucket_edges():
    reg = MetricsRegistry(0)
    h = reg.histogram("edges")
    h.observe(0.0)       # non-positive -> bucket 0
    h.observe(-3.0)
    h.observe(2.0 ** 50)  # beyond the top bound -> clamped to last bucket
    assert h.count == 3
    top = h.nonzero_buckets()[-1][0]
    assert top == bucket_upper_bound(63)


def test_registry_thread_safety_under_concurrent_workers():
    """The stream-worker scenario: N threads hammering one counter and
    one histogram concurrently must lose no updates."""
    reg = MetricsRegistry(0)
    c = reg.counter("hits_total")
    h = reg.histogram("lat_ms")
    n_threads, per_thread = 8, 5000

    def worker(k):
        for i in range(per_thread):
            c.inc()
            h.observe(float(i % 7) + 0.5)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread
    assert sum(n for _, n in h.nonzero_buckets()) == h.count


def test_prometheus_exposition_golden_file():
    reg = MetricsRegistry(0)
    reg.counter("horovod_autoscale_decisions_total", "Autoscale decisions",
                labels={"direction": "up"}).inc()
    h_catch = reg.histogram("horovod_catch_up_ms",
                            "Joiner bulk catch-up wall time")
    h_catch.observe(850.0)
    reg.counter("horovod_statesync_bytes_total", "State bytes streamed",
                labels={"role": "donor"}).inc(4096)
    reg.counter("horovod_statesync_bytes_total",
                labels={"role": "joiner"}).inc(4096)
    reg.gauge("horovod_world_size", "Live world size").set(4)
    # Rendezvous control plane (ISSUE 15): per-replica role, promotion
    # counter, and the per-peer wire proto gauge of the HELLO handshake.
    reg.gauge("horovod_rendezvous_role",
              "1 while this replica is the rendezvous primary, 0 as "
              "standby", labels={"replica": "0"}).set(1)
    reg.gauge("horovod_rendezvous_role",
              labels={"replica": "1"}).set(0)
    reg.counter("horovod_rendezvous_failovers_total",
                "Leader promotions this replica performed").inc()
    reg.gauge("horovod_wire_proto_version",
              "Wire protocol version the peer advertised at channel "
              "establishment",
              labels={"mesh": "ctrl0", "peer": "1"}).set(2)
    for state, n in (("free", 24), ("active", 6), ("cached", 2)):
        reg.gauge("horovod_serve_kv_blocks", "Paged KV blocks by state",
                  labels={"state": state}).set(n)
    reg.counter("horovod_serve_prefix_hits_total",
                "Prompt blocks served from the prefix cache").inc(5)
    reg.counter("horovod_serve_prefix_misses_total",
                "Prompt blocks prefilled fresh").inc(3)
    reg.counter("horovod_serve_prefill_stream_bytes_total",
                "KV bytes streamed prefill->decode",
                labels={"role": "sent"}).inc(8192)
    # Core-dispatch collective metrics (ISSUE 18): the latency histogram
    # carries the algo label and the per-algorithm verdict counter rides
    # next to it.
    reg.histogram("horovod_collective_latency_ms",
                  "End-to-end latency of one executed response, by data "
                  "plane, op, wire codec and collective algorithm",
                  labels={"plane": "tcp", "op": "allreduce",
                          "codec": "none", "algo": "tree"}).observe(2.0)
    reg.counter("horovod_collective_algo_total",
                "Executed responses by collective algorithm (ring / tree "
                "/ rhd / torus / hierarchical / ... — the per-size "
                "selection verdict)", labels={"algo": "tree"}).inc(1)
    # perfscope roofline metrics (ISSUE 19): the busbw histogram with
    # the size-bucket axis, the self-calibrated peak gauge, and the
    # efficiency/MFU gauges the PERF.json ledger merges.
    reg.histogram("horovod_collective_busbw_mbps",
                  "Bus bandwidth of one executed collective (MB/s, "
                  "nccl-tests convention)",
                  labels={"plane": "tcp", "op": "allreduce",
                          "codec": "none", "algo": "ring",
                          "size_bucket": "1MiB"}).observe(260.0)
    reg.gauge("horovod_collective_busbw_peak_mbps",
              "Best demonstrated bus bandwidth — the self-calibrated "
              "roofline").set(314.6)
    reg.gauge("horovod_collective_efficiency",
              "Latest bus bandwidth over the roofline",
              labels={"plane": "tcp", "algo": "ring",
                      "size_bucket": "1MiB"}).set(0.83)
    reg.gauge("horovod_train_mfu",
              "Model-FLOPs utilization of the last train step").set(0.41)
    reg.counter("hvd_test_bytes_total", "Bytes moved",
                labels={"peer": "1"}).inc(2048)
    reg.counter("hvd_test_bytes_total", labels={"peer": "2"}).inc(1024)
    reg.gauge("hvd_test_depth", "Queue depth").set(7)
    h = reg.histogram("hvd_test_latency_ms", "Latency")
    for v in (0.5, 1.5, 3.0, 12.0):
        h.observe(v)
    with open(os.path.join(FIXTURES, "exposition.prom")) as f:
        golden = f.read()
    assert reg.render_prometheus() == golden


def test_null_registry_is_inert():
    assert NULL_REGISTRY.enabled is False
    assert NULL_REGISTRY.counter("x") is NULL_METRIC
    assert NULL_REGISTRY.histogram("y") is NULL_METRIC
    NULL_METRIC.inc(5)
    NULL_METRIC.observe(1.0)
    NULL_METRIC.set(2.0)
    assert NULL_METRIC.value == 0.0
    assert NULL_REGISTRY.snapshot()["metrics"] == []
    assert NULL_REGISTRY.render_prometheus() == ""


# --- straggler aggregation --------------------------------------------------
def test_straggler_window_names_slowest_rank():
    reg = MetricsRegistry(0)
    agg = StragglerAggregator(4, reg, window=4, threshold_ms=5.0)
    t0 = 1000.0
    for _ in range(4):
        agg.observe_tensor({0: t0, 1: t0 + 0.001, 2: t0 + 0.002,
                            3: t0 + 0.050})
        t0 += 1.0
    assert agg.windows_completed == 1
    assert agg.last_straggler == 3
    assert 45.0 < agg.last_skew_ms < 55.0
    assert reg.gauge("horovod_controller_straggler_rank").value == 3.0
    assert reg.gauge("horovod_controller_straggler_lag_ms").value > 45.0
    assert reg.counter(
        "horovod_controller_straggler_windows_total").value == 1
    p99 = reg.gauge("horovod_controller_negotiation_lag_ms",
                    labels={"stat": "p99"}).value
    assert 45.0 < p99 < 55.0


def test_straggler_below_threshold_clears_gauge():
    reg = MetricsRegistry(0)
    agg = StragglerAggregator(2, reg, window=2, threshold_ms=5.0)
    for _ in range(2):
        agg.observe_tensor({0: 1.0, 1: 1.0 + 0.0005})   # 0.5 ms skew
    assert agg.windows_completed == 1
    assert reg.gauge("horovod_controller_straggler_rank").value == -1.0
    assert reg.counter(
        "horovod_controller_straggler_windows_total").value == 0


def test_straggler_snapshot_gauges():
    reg = MetricsRegistry(0)
    agg = StragglerAggregator(2, reg, window=8)
    gathered = [
        RequestList(tm_cycles=10, tm_cycle_ms=25.0, tm_sync_wait_ms=5.0,
                    tm_queue_depth=3),
        RequestList(tm_cycles=5, tm_cycle_ms=50.0, tm_sync_wait_ms=0.5,
                    tm_queue_depth=0),
    ]
    agg.observe_snapshots(gathered)
    assert reg.gauge("horovod_rank_cycle_ms",
                     labels={"rank": "0"}).value == pytest.approx(2.5)
    assert reg.gauge("horovod_rank_cycle_ms",
                     labels={"rank": "1"}).value == pytest.approx(10.0)
    assert reg.gauge("horovod_rank_sync_wait_ms",
                     labels={"rank": "1"}).value == pytest.approx(0.1)
    assert reg.gauge("horovod_rank_queue_depth",
                     labels={"rank": "0"}).value == 3.0


# --- wire snapshot ----------------------------------------------------------
def test_request_list_tm_fields_roundtrip():
    rl = RequestList(tm_cycles=17, tm_cycle_ms=42.5,
                     tm_sync_wait_ms=3.25, tm_queue_depth=9)
    decoded = RequestList.from_bytes(rl.to_bytes())
    assert decoded.tm_cycles == 17
    assert decoded.tm_cycle_ms == 42.5
    assert decoded.tm_sync_wait_ms == 3.25
    assert decoded.tm_queue_depth == 9
    # Defaults stay zero (metrics off ships an all-zero snapshot).
    empty = RequestList.from_bytes(RequestList().to_bytes())
    assert (empty.tm_cycles, empty.tm_cycle_ms,
            empty.tm_sync_wait_ms, empty.tm_queue_depth) == (0, 0.0, 0.0, 0)


# --- exporter + dump + report ----------------------------------------------
def test_exporter_scrape_and_close():
    from horovod_tpu.runner.network import free_port
    reg = MetricsRegistry(0)
    reg.counter("x_total", "help").inc(3)
    exp = MetricsExporter(reg, rank=0, base_port=free_port())
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/metrics", timeout=10
        ).read().decode()
        assert "x_total 3" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=10)
    finally:
        exp.close()


def test_exporter_port_conflict_falls_back_to_ephemeral():
    reg = MetricsRegistry(0)
    a = MetricsExporter(reg, rank=0, base_port=0)   # ephemeral
    try:
        b = MetricsExporter(reg, rank=0, base_port=a.port)  # busy -> fallback
        try:
            assert b.port != a.port and b.port > 0
        finally:
            b.close()
    finally:
        a.close()


def test_resolve_dump_path():
    assert resolve_dump_path("/tmp/m_{rank}.json", 3) == "/tmp/m_3.json"
    assert resolve_dump_path("/tmp/m.json", 2) == "/tmp/m.r2.json"
    assert resolve_dump_path("/tmp/m", 1) == "/tmp/m.r1"


def test_dump_json_and_report_cli(tmp_path):
    reg = MetricsRegistry(1)
    reg.counter("bytes_total", labels={"peer": "0"}).inc(100)
    reg.histogram("lat_ms").observe(2.0)
    path = dump_json(reg, str(tmp_path / "m.json"), 1)
    assert path.endswith("m.r1.json")
    out = summarize_file(path)
    assert "bytes_total" in out and "lat_ms" in out
    # Dump payload carries full histogram detail.
    snap = json.loads((tmp_path / "m.r1.json").read_text())
    hist = next(m for m in snap["metrics"] if m["name"] == "lat_ms")
    assert hist["count"] == 1 and hist["buckets"] == [[2.0, 1]]


def test_report_summarizes_timeline_spans():
    events = [
        {"ph": "B", "name": "ALLREDUCE", "ts": 0, "pid": 0, "tid": 0},
        {"ph": "B", "name": "TCP_RING_ALLREDUCE", "ts": 100, "pid": 0,
         "tid": 0},
        {"ph": "E", "name": "", "ts": 4100, "pid": 0, "tid": 0},
        {"ph": "E", "name": "", "ts": 5000, "pid": 0, "tid": 0},
        {"ph": "C", "name": "tensor_queue_depth", "ts": 5000, "pid": 0,
         "args": {"depth": 2}},
    ]
    out = summarize_timeline(events)
    assert "ALLREDUCE" in out and "TCP_RING_ALLREDUCE" in out
    assert "5.00" in out      # ALLREDUCE total 5 ms
    assert "4.00" in out      # nested ring span 4 ms
    assert "tensor_queue_depth" in out


def test_report_summarizes_empty_dump():
    out = summarize_dump({"rank": 0, "metrics": []})
    assert "HOROVOD_METRICS=on" in out


# --- timeline counter events + batched writer -------------------------------
def test_timeline_counter_events_and_batched_writer(tmp_path):
    path = tmp_path / "tl.json"
    tl = Timeline(str(path))
    # Well past the write batch size: the writer must batch without
    # losing events, and stop() must drain everything (unbounded join).
    for i in range(200):
        tl.activity_start(f"t{i % 5}", "OP")
        tl.activity_end(f"t{i % 5}")
    tl.counter("tensor_queue_depth", {"depth": 3})
    tl.counter("wire_bytes", {"sent": 10, "received": 20})
    tl.stop()
    events = json.loads(path.read_text())
    assert sum(1 for e in events if e.get("ph") == "B") == 200
    assert sum(1 for e in events if e.get("ph") == "E") == 200
    counters = [e for e in events if e.get("ph") == "C"]
    assert len(counters) == 2
    assert counters[0]["args"] == {"depth": 3}
    assert counters[1]["args"] == {"sent": 10, "received": 20}
    assert all("ts" in e for e in counters)


# --- HOROVOD_METRICS=off no-op contract -------------------------------------
def test_metrics_off_world_is_noop(monkeypatch):
    """With the knob off: Null registry, no exporter thread, no metrics
    anywhere — the thread census is exactly the no-telemetry baseline."""
    monkeypatch.delenv("HOROVOD_METRICS", raising=False)
    monkeypatch.delenv("HOROVOD_METRICS_PORT", raising=False)
    monkeypatch.delenv("HOROVOD_METRICS_FILE", raising=False)
    import horovod_tpu as hvd
    from horovod_tpu import core

    from census import assert_no_new_threads, assert_thread_absent, \
        thread_names
    before = thread_names()
    hvd.init()
    try:
        st = core.global_state()
        assert st.telemetry is NULL_REGISTRY
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name="tm_off")
        np.testing.assert_allclose(out, np.ones(4))
        assert_thread_absent("hvd-metrics")
        # Only the background loop was added to the census.
        assert_no_new_threads(before, allow={"hvd-background"},
                              context="metrics-off world")
        assert st.telemetry.snapshot()["metrics"] == []
    finally:
        hvd.shutdown()


def test_metrics_on_world_records(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS", "on")
    monkeypatch.delenv("HOROVOD_METRICS_PORT", raising=False)
    import horovod_tpu as hvd
    from horovod_tpu import core, telemetry

    hvd.init()
    try:
        st = core.global_state()
        assert st.telemetry.enabled
        for i in range(3):
            hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum,
                          name="tm_on")
        names = {m["name"] for m in st.telemetry.snapshot()["metrics"]}
        assert "horovod_controller_cycle_ms" in names
        assert "horovod_collective_latency_ms" in names
        assert "horovod_controller_cache_hit_total" in names
        summ = telemetry.summary()
        assert summ["cache_hit_rate"] > 0.0
        assert "stream_busy_ms" in summ
    finally:
        hvd.shutdown()
