"""Fused computation-collective kernel battery (ISSUE 6).

Covers the tentpole contracts:

- fused single-pass codec legs are BITWISE identical to the reference
  per-chunk dequant/requant chain for every codec (bf16 cast, int8/uint4
  quantized) on 2- and 4-rank worlds (same fp32 ops, same rank-order
  accumulation), and the fused encode emits byte-identical wire images;
- quantized fused legs stay within the documented per-codec
  roundtrip_error_bound of the exact fp32 sum;
- optimizer-in-ring (sync_and_apply / Trainer opt-in): params after one
  fused step match sync-then-update within fp32 tolerance, with the
  optimizer state sharded ZeRO-style;
- fused loss-scaling/unscaling + global-norm clipping inside the sync
  pass matches optax.clip_by_global_norm on unscaled gradients;
- the autotuner sweeps fused on/off and the winner rides
  ResponseList.tuned_fused;
- hvdlint HVD1004 flags per-segment codec loops in backend/ (fixture);
- (slow) the 4-rank 4 MiB int8 A/B: fused beats the PR 3 pipelined
  reference chain (measured ~1.27x at authoring time; acceptance floor
  1.15x).
"""
from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.backend.tcp import TcpCollectives
from horovod_tpu.compress import (CompressionCodec, dequantize, from_bytes,
                                  quantize, roundtrip_error_bound, to_bytes)
from horovod_tpu.compress.fused import FusedKernels
from horovod_tpu.runner.network import PeerMesh

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def kv():
    from horovod_tpu.runner.network import (RendezvousClient,
                                            RendezvousServer)
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 15.0)
    server.stop()


def _threaded(n, fn, timeout=90.0):
    results: list = [None] * n
    errors: list = []

    def worker(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    if errors:
        raise errors[0]
    return results


def _world(kv, size, scope, fn, timeout=90.0):
    meshes: list = [None] * size

    def worker(r):
        meshes[r] = PeerMesh(r, size, kv, scope=scope, timeout=15.0)
        return fn(TcpCollectives(meshes[r]), r)

    try:
        return _threaded(size, worker, timeout=timeout)
    finally:
        for m in meshes:
            if m is not None:
                m.close()


# ---------------------------------------------------------------------------
# Kernel-level parity: fused encode/decode == quantize.py, bitwise
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("codec", [CompressionCodec.INT8,
                                   CompressionCodec.UINT4])
@pytest.mark.parametrize("n", [1, 7, 128, 1251, 5000])
def test_fused_encode_wire_byte_parity(codec, n):
    """The fused requantize emits the EXACT wire image of
    to_bytes(quantize(x)) — scales || zero_points || payload, including
    the zero pad nibble of odd-length uint4 buffers — so fused and
    reference ranks interoperate frame-for-frame."""
    rng = np.random.default_rng(100 + n)
    fk = FusedKernels()
    for bs in (64, 128):
        x = (rng.standard_normal(n) * 3).astype(np.float32)
        assert fk.encode(x, codec, bs, ("t",)).tobytes() == \
            to_bytes(quantize(x, codec, bs))


@pytest.mark.parametrize("codec", [CompressionCodec.INT8,
                                   CompressionCodec.UINT4])
def test_fused_decode_add_matches_reference(codec):
    rng = np.random.default_rng(7)
    fk = FusedKernels()
    n, bs = 1251, 64
    x = (rng.standard_normal(n) * 2).astype(np.float32)
    wire = fk.encode(x, codec, bs, ("t",))
    ref = dequantize(from_bytes(np.frombuffer(wire.tobytes(), np.uint8),
                                n, codec, bs))
    out = np.empty(n, np.float32)
    fk.decode_into(wire, n, codec, bs, out, ("d",))
    np.testing.assert_array_equal(out, ref)
    acc = rng.standard_normal(n).astype(np.float32)
    expect = acc + ref
    fk.decode_add(wire, n, codec, bs, acc, ("d",))
    np.testing.assert_array_equal(acc, expect)


def test_fused_scratch_is_reused():
    """Steady-state kernels allocate nothing: the same geometry returns
    the identical scratch buffers on every call."""
    fk = FusedKernels()
    a = fk.f32(("k",), 100)
    b = fk.f32(("k",), 100)
    assert a.base is b.base or a is b
    big = fk.f32(("k",), 1000)          # growth reallocates...
    again = fk.f32(("k",), 1000)
    assert big.base is again.base or big is again


# ---------------------------------------------------------------------------
# Plane-level parity: fused vs reference dispatch, bitwise, 2/4 ranks
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("size", [2, 4])
@pytest.mark.parametrize("codec", ["bf16", "int8", "uint4"])
def test_fused_vs_reference_bitwise(kv, codec, size):
    """The acceptance contract: flipping HOROVOD_FUSED_KERNELS changes
    WHERE the codec math runs (inside the collective pass vs around it),
    never a single output bit."""
    rng = np.random.default_rng(4321 + size)
    n = 12345            # odd => uneven chunks + odd uint4 tails
    data = (rng.standard_normal((size, n)) * 5).astype(np.float32)

    def op(coll, r):
        if codec == "bf16":
            import ml_dtypes
            return coll.cast_allreduce(data[r].copy(),
                                       np.dtype(ml_dtypes.bfloat16))
        qc = CompressionCodec.INT8 if codec == "int8" \
            else CompressionCodec.UINT4
        return coll.quantized_allreduce(data[r].copy(), qc, 128)

    def run(scope, fused):
        def fn(coll, r):
            coll.fused = fused
            return op(coll, r)
        return _world(kv, size, scope, fn)

    fused = run(f"fp-{codec}-{size}-f", True)
    ref = run(f"fp-{codec}-{size}-r", False)
    for r in range(size):
        np.testing.assert_array_equal(np.asarray(fused[r]),
                                      np.asarray(ref[r]))
    # Symmetric-result contract holds on the fused path too.
    for r in range(1, size):
        np.testing.assert_array_equal(np.asarray(fused[0]),
                                      np.asarray(fused[r]))


def test_fused_and_reference_ranks_interoperate(kv):
    """Both dispatch settings move one frame per peer per leg and encode
    byte-identical wire images, so a world where the knob disagrees
    (e.g. mid-flip of the autotuned ResponseList) still reduces
    correctly and bitwise-symmetrically."""
    size, n = 3, 4000
    rng = np.random.default_rng(9)
    data = (rng.standard_normal((size, n)) * 2).astype(np.float32)

    def fn(coll, r):
        coll.fused = r % 2 == 0          # ranks disagree on purpose
        return coll.quantized_allreduce(data[r].copy(),
                                        CompressionCodec.INT8, 128)

    outs = _world(kv, size, "interop", fn)
    for r in range(1, size):
        np.testing.assert_array_equal(outs[0], outs[r])


def test_shm_fused_vs_reference_bitwise(kv):
    """The shm plane carries the same fused/reference dispatch (its
    `fused` attribute, autotuner-flippable): both settings stage
    byte-identical regions and reconstruct bit-identically."""
    from horovod_tpu.backend.shm import ShmBackend, ShmWorld
    from horovod_tpu.common.dtypes import from_any
    from horovod_tpu.common.message import Response, ResponseType
    from horovod_tpu.common.tensor_queue import TensorTableEntry

    size, n = 2, 3000
    rng = np.random.default_rng(12)
    data = rng.standard_normal((size, n)).astype(np.float32)
    worlds = _threaded(size, lambda r: ShmWorld(
        r, size, kv, scope="sf", capacity=1 << 20, timeout=10.0))
    if not all(w.formed for w in worlds):
        pytest.skip("shm world did not form on this host")
    try:
        outs: dict[bool, list] = {}
        for fused in (True, False):
            def run(r, fused=fused):
                be = ShmBackend(worlds[r])
                be.fused = fused
                resp = Response(
                    response_type=ResponseType.ALLREDUCE,
                    tensor_names=["x"], tensor_sizes=[n],
                    tensor_type=from_any(np.dtype(np.float32)),
                    codec=int(CompressionCodec.INT8),
                    codec_block_size=128)
                e = TensorTableEntry(tensor_name="x",
                                     tensor=data[r].copy())
                assert be.allreduce(resp, [e]).ok_p()
                return e.output

            outs[fused] = _threaded(size, run)
        np.testing.assert_array_equal(outs[True][0], outs[False][0])
        np.testing.assert_array_equal(outs[True][0], outs[True][1])
    finally:
        for w in worlds:
            w.close()


@pytest.mark.parametrize("codec", [CompressionCodec.INT8,
                                   CompressionCodec.UINT4])
def test_fused_quantized_within_error_bound(kv, codec):
    """Bounded-error assertion per codec: the fused plane's deviation
    from the exact fp32 sum obeys the documented per-element bound
    (input quantization of each rank + one output requantization)."""
    from horovod_tpu.compress import chunk_bounds
    size, n, bs = 3, 5000, 128
    rng = np.random.default_rng(17)
    data = (rng.standard_normal((size, n)) * 3).astype(np.float32)

    def fn(coll, r):
        coll.fused = True
        return coll.quantized_allreduce(data[r].copy(), codec, bs)

    outs = _world(kv, size, f"bound{int(codec)}", fn)
    exact = data.sum(axis=0)
    input_bound = sum(roundtrip_error_bound(data[r], codec, bs)
                      for r in range(size))
    b = chunk_bounds(n, size)
    requant = np.concatenate(
        [roundtrip_error_bound(exact[b[r]:b[r + 1]], codec, bs)
         for r in range(size)])
    bound = 2 * input_bound + requant + 1e-5
    err = np.abs(outs[0].astype(np.float64) - exact)
    assert np.all(err <= bound), float(err.max())


def test_fused_leg_latency_histograms(kv, monkeypatch):
    """Telemetry satellite: the codec legs record per-leg wall time under
    {leg, fused} labels so the fusion win shows up in the metrics dump."""
    from horovod_tpu import telemetry
    monkeypatch.setenv("HOROVOD_METRICS", "on")
    telemetry.configure(0)
    try:
        size, n = 2, 4000
        rng = np.random.default_rng(3)
        data = rng.standard_normal((size, n)).astype(np.float32)

        def fn(coll, r):
            for fused in (True, False):
                coll.fused = fused
                coll.quantized_allreduce(data[r].copy(),
                                         CompressionCodec.INT8, 128)
            return coll

        _world(kv, size, "tmleg", fn)
        reg = telemetry.metrics()
        counts = {}
        for entry in reg.snapshot()["metrics"]:
            if entry["name"] == "horovod_tcp_codec_leg_ms":
                key = (entry["labels"]["leg"], entry["labels"]["fused"])
                counts[key] = counts.get(key, 0) + entry["count"]
        for leg in ("gather", "return"):
            for fused in ("on", "off"):
                assert counts.get((leg, fused), 0) > 0, (leg, fused,
                                                         counts)
    finally:
        monkeypatch.delenv("HOROVOD_METRICS")
        telemetry.configure(0)


# ---------------------------------------------------------------------------
# Optimizer-in-ring (compiled plane; virtual CPU mesh from conftest)
# ---------------------------------------------------------------------------
def _dp_mesh(n):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def _ring_world_run(world, grads, params, tx, cfg):
    """Run sync_and_apply under shard_map with stacked per-rank opt
    state; returns (new_params by rank 0, per-rank equality checked)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import (init_ring_optimizer_state,
                                      sync_and_apply)

    mesh = _dp_mesh(world)
    os0 = init_ring_optimizer_state(tx, params, world, cfg)
    os_stacked = jax.tree_util.tree_map(
        lambda l: jnp.broadcast_to(l, (world,) + l.shape)
        if getattr(l, "ndim", 0) >= 1 else l, os0)
    os_specs = jax.tree_util.tree_map(
        lambda l: P("dp") if getattr(l, "ndim", 0) >= 2 else P(),
        os_stacked)
    p_stacked = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(jnp.asarray(x), (world,) + x.shape),
        params)

    def step(g, p, s):
        p_local = jax.tree_util.tree_map(lambda x: x[0], p)
        s_local = jax.tree_util.tree_map(
            lambda l: l[0] if getattr(l, "ndim", 0) >= 2 else l, s)
        new_p, new_s = sync_and_apply(tx, g, p_local, s_local, cfg)
        return (jax.tree_util.tree_map(lambda x: x[None], new_p),
                jax.tree_util.tree_map(
                    lambda l: l[None] if getattr(l, "ndim", 0) >= 1
                    else l, new_s))

    fn = jax.jit(shard_map(step, mesh=mesh,
                           in_specs=(P("dp"), P("dp"), os_specs),
                           out_specs=(P("dp"), os_specs),
                           check_vma=False))
    new_p, new_s = fn(grads, p_stacked, os_stacked)
    for leaf in jax.tree_util.tree_leaves(new_p):
        arr = np.asarray(leaf)
        for r in range(1, world):
            np.testing.assert_array_equal(arr[0], arr[r])
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[0], new_p), \
        new_s


@pytest.mark.parametrize("world", [2, 4])
def test_optimizer_in_ring_matches_sync_then_update(world):
    """Acceptance: params after one optimizer-in-ring step (update on
    the reduce-scattered shard, updated params on the allgather) match
    sync-then-update within fp32 tolerance on 2/4-rank worlds."""
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import GradSyncConfig, sync_gradients

    rng = np.random.default_rng(20 + world)
    grads = {"w": (rng.standard_normal((world, 33, 7)) * 2).astype(
        np.float32),
        "b": rng.standard_normal((world, 11)).astype(np.float32)}
    params = {"w": rng.standard_normal((33, 7)).astype(np.float32),
              "b": rng.standard_normal((11,)).astype(np.float32)}
    tx = optax.adam(1e-2)

    # Reference: replicated sync, then a replicated update.
    import jax.numpy as jnp
    mesh = _dp_mesh(world)
    ref_cfg = GradSyncConfig(axes=("dp",), op="average")
    synced = jax.jit(shard_map(
        lambda g: sync_gradients(g, ref_cfg), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False))(grads)
    g0 = {k: jnp.asarray(np.asarray(v)[0]) for k, v in synced.items()}
    upd, _ = tx.update(g0, tx.init(params), params)
    import optax as _optax
    p_ref = _optax.apply_updates(params, upd)

    cfg = GradSyncConfig(axes=("dp",), op="average",
                         optimizer_in_ring=True)
    p_ring, _ = _ring_world_run(world, grads, params, tx, cfg)
    for k in params:
        np.testing.assert_allclose(p_ring[k], np.asarray(p_ref[k]),
                                   rtol=2e-6, atol=2e-6)


def test_optimizer_in_ring_int8_gradient_leg():
    """Quantized codec on the gradient reduce-scatter leg: the ring
    update must match quantized-sync-then-update within the codec's
    error bound (loose check: small relative deviation on a smooth
    surface; exactness is pinned by the fp32 test above)."""
    import jax.numpy as jnp
    import optax
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import GradSyncConfig, sync_gradients

    world = 4
    rng = np.random.default_rng(31)
    grads = {"w": rng.standard_normal((world, 64)).astype(np.float32)}
    params = {"w": rng.standard_normal((64,)).astype(np.float32)}
    tx = optax.sgd(0.1)

    cfg = GradSyncConfig(axes=("dp",), op="average", compression="int8",
                         compression_block_size=64,
                         optimizer_in_ring=True)
    p_ring, _ = _ring_world_run(world, grads, params, tx, cfg)

    mesh = _dp_mesh(world)
    qcfg = GradSyncConfig(axes=("dp",), op="average", compression="int8",
                          compression_block_size=64)
    synced = jax.jit(shard_map(
        lambda g: sync_gradients(g, qcfg), mesh=mesh, in_specs=P("dp"),
        out_specs=P("dp"), check_vma=False))(grads)
    g0 = jnp.asarray(np.asarray(synced["w"])[0])
    # SGD: p' = p - lr*g; both paths see int8-quantized reduced grads
    # within the same block bound.
    expect = params["w"] - 0.1 * np.asarray(g0)
    bound = 0.1 * 2 * np.max(np.abs(
        roundtrip_error_bound(np.asarray(g0), CompressionCodec.INT8,
                              64))) + 1e-5
    assert np.max(np.abs(p_ring["w"] - expect)) <= bound


def test_optimizer_in_ring_rejections():
    import optax

    from horovod_tpu.parallel import GradSyncConfig, sync_and_apply

    tx = optax.adam(1e-3)
    g = {"w": np.ones(4, np.float32)}
    with pytest.raises(ValueError, match="adasum|sum\\|average"):
        sync_and_apply(tx, g, g, None,
                       GradSyncConfig(axes=("dp",), op="adasum",
                                      optimizer_in_ring=True))
    with pytest.raises(ValueError, match="error-feedback"):
        sync_and_apply(tx, g, g, None,
                       GradSyncConfig(axes=("dp",), op="average",
                                      error_feedback=True,
                                      compression="int8",
                                      optimizer_in_ring=True))
    with pytest.raises(ValueError, match="axes"):
        sync_and_apply(tx, g, g, None,
                       GradSyncConfig(axes=(), op="average",
                                      optimizer_in_ring=True))


def test_trainer_optimizer_in_ring_step():
    """Trainer opt-in: one compiled step with optimizer_in_ring matches
    the plain Trainer bit-for-bit within fp32 tolerance, and the ring
    optimizer state is sharded (stacked world leading dim)."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu import training
    from horovod_tpu.parallel import GradSyncConfig

    class Tiny:
        def init(self, rng, x, train=False):
            k = jax.random.key(0)
            return {"params": {
                "w": jax.random.normal(k, (x.shape[-1], 5),
                                       jnp.float32) * 0.1,
                "b": jnp.zeros((5,), jnp.float32)}}

        def apply(self, variables, x, train=False, mutable=False):
            p = variables["params"]
            return x @ p["w"] + p["b"]

    mesh = _dp_mesh(4)
    rng = np.random.default_rng(0)
    batch = {"input": rng.standard_normal((8, 3)).astype(np.float32),
             "label": (np.arange(8) % 5).astype(np.int32)}

    ref = training.Trainer(Tiny(), optax.adam(1e-2), mesh,
                           sync=GradSyncConfig(axes=("dp",),
                                               op="average"))
    s_ref, _ = ref.step(ref.init(jax.random.key(0), batch), batch)

    ring = training.Trainer(
        Tiny(), optax.adam(1e-2), mesh,
        sync=GradSyncConfig(axes=("dp",), op="average",
                            optimizer_in_ring=True))
    s0 = ring.init(jax.random.key(0), batch)
    # ZeRO layout: moment leaves are stacked (world, chunk).
    mu_leaves = [leaf for leaf in jax.tree_util.tree_leaves(s0.opt_state)
                 if getattr(leaf, "ndim", 0) >= 2]
    assert mu_leaves and all(leaf.shape[0] == 4 for leaf in mu_leaves)
    s1, _ = ring.step(s0, batch)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(s1.params[k]),
                                   np.asarray(s_ref.params[k]),
                                   rtol=2e-6, atol=2e-6)
    s2, _ = ring.step(s1, batch)           # state threads through
    assert float(jnp.sum(s2.step)) > 0


# ---------------------------------------------------------------------------
# Fused loss-scaling + global-norm clipping
# ---------------------------------------------------------------------------
def test_fused_scale_clip_matches_optax():
    """sync_gradients with loss_scale+clip_global_norm == allreduce,
    then unscale, then optax.clip_by_global_norm — but in ONE pass."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import GradSyncConfig, sync_gradients

    world, S, C = 4, 256.0, 0.75
    mesh = _dp_mesh(world)
    rng = np.random.default_rng(5)
    grads = {"w": (rng.standard_normal((world, 33, 7)) * 2).astype(
        np.float32),
        "b": rng.standard_normal((world, 11)).astype(np.float32)}

    ref_cfg = GradSyncConfig(axes=("dp",), op="average")
    synced = jax.jit(shard_map(
        lambda g: sync_gradients(g, ref_cfg), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False))(grads)
    unscaled = {k: jnp.asarray(np.asarray(v)[0])
                for k, v in synced.items()}
    clipper = optax.clip_by_global_norm(C)
    expect, _ = clipper.update(unscaled, clipper.init(unscaled))

    cfg = GradSyncConfig(axes=("dp",), op="average", loss_scale=S,
                         clip_global_norm=C)
    scaled = {k: v * S for k, v in grads.items()}
    out = jax.jit(shard_map(
        lambda g: sync_gradients(g, cfg), mesh=mesh, in_specs=P("dp"),
        out_specs=P("dp"), check_vma=False))(scaled)
    for k in grads:
        np.testing.assert_allclose(np.asarray(out[k])[0],
                                   np.asarray(expect[k]),
                                   rtol=2e-5, atol=2e-6)


def test_fused_scale_only_unscales():
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import GradSyncConfig, sync_gradients

    world, S = 2, 64.0
    mesh = _dp_mesh(world)
    rng = np.random.default_rng(6)
    grads = {"w": rng.standard_normal((world, 40)).astype(np.float32)}
    cfg = GradSyncConfig(axes=("dp",), op="average", loss_scale=S)
    out = jax.jit(shard_map(
        lambda g: sync_gradients(g, cfg), mesh=mesh, in_specs=P("dp"),
        out_specs=P("dp"), check_vma=False))(
            {"w": grads["w"] * S})
    np.testing.assert_allclose(np.asarray(out["w"])[0],
                               grads["w"].mean(axis=0),
                               rtol=2e-6, atol=2e-6)


def test_fused_scale_clip_threads_through_ef():
    """sync_gradients_ef + clipping: the EF residual tracks the WIRE
    (pre-factor) error while outputs carry the clip factor — clipping
    must not corrupt residual bookkeeping (finite, bounded residuals)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import (GradSyncConfig, init_error_feedback,
                                      sync_gradients_ef)

    world = 2
    mesh = _dp_mesh(world)
    rng = np.random.default_rng(8)
    grads = {"w": rng.standard_normal((world, 256)).astype(np.float32)}
    cfg = GradSyncConfig(axes=("dp",), op="average", compression="int8",
                         compression_block_size=64, error_feedback=True,
                         clip_global_norm=0.5)

    def step(g, res):
        return sync_gradients_ef(g, res, cfg)

    res0 = jax.tree_util.tree_map(
        lambda x: jnp.zeros_like(x), grads)
    out, res = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False))(grads, res0)
    assert np.all(np.isfinite(np.asarray(out["w"])))
    # Output norm respects the clip.
    gn = float(np.linalg.norm(np.asarray(out["w"])[0]))
    assert gn <= 0.5 + 1e-4, gn
    # Residual stays the wire-space quantization error (bounded by the
    # block bound of the compensated gradients, NOT scaled by the clip).
    bound = roundtrip_error_bound(
        np.asarray(grads["w"][0]), CompressionCodec.INT8, 64)
    assert np.all(np.abs(np.asarray(res["w"])[0]) <=
                  np.max(bound) * 4 + 1e-4)
    del init_error_feedback


def test_adasum_rejects_fused_scale_clip():
    from horovod_tpu.parallel import GradSyncConfig, sync_gradients

    with pytest.raises(ValueError, match="adasum"):
        sync_gradients({"w": np.ones(4, np.float32)},
                       GradSyncConfig(axes=("dp",), op="adasum",
                                      loss_scale=8.0))


# ---------------------------------------------------------------------------
# Autotuner fused sweep + wire plumbing
# ---------------------------------------------------------------------------
def test_autotune_fused_sweep(monkeypatch):
    """After the pipeline sweep, fused on/off each get one sample window
    and the better-scoring setting is pinned via pending_tuned_fused."""
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "0")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_PIPELINE", "1")
    monkeypatch.setenv("HOROVOD_NUM_STREAMS", "1")
    from horovod_tpu.common.parameter_manager import ParameterManager

    class Ctrl:
        tensor_fusion_threshold = 1 << 26
        pending_tuned_params = None
        pending_tuned_codec = None
        pending_tuned_pipeline = None
        pending_tuned_fused = None

    ctrl = Ctrl()
    pm = ParameterManager(ctrl, active=True)
    assert pm._fused_candidates == [1, 0]
    # Drain the pipeline sweep first (4 segments x 1 width + winner).
    n_pipe = len(pm._pipeline_candidates)
    for _ in range(n_pipe + 1):
        pm.observe(["t"], 1 << 20)
        ctrl.pending_tuned_pipeline = None
    proposals = []
    for _ in range(3):                   # on, off, winner
        pm.observe(["t"], 1 << 20)
        assert ctrl.pending_tuned_fused is not None
        proposals.append(ctrl.pending_tuned_fused)
        ctrl.pending_tuned_fused = None
    assert proposals[:2] == [1, 0]
    assert proposals[2] in (0, 1)
    assert not pm._fused_candidates


def test_tuned_fused_rides_response_list_wire():
    from horovod_tpu.common.message import ResponseList

    rl = ResponseList(tuned_fused=1)
    assert ResponseList.from_bytes(rl.to_bytes()).tuned_fused == 1
    # Default means "unchanged" on every rank.
    assert ResponseList.from_bytes(
        ResponseList().to_bytes()).tuned_fused == -1


def test_tuned_fused_applies_to_collectives(kv):
    """core applies ResponseList.tuned_fused to every TcpCollectives —
    simulated here at the collectives level (the background-loop hookup
    mirrors tuned_segment_bytes, exercised by the streams battery)."""
    import horovod_tpu.core as core

    class _Coll:
        fused = False

    st = core.global_state()
    saved = st.tcp_collectives
    try:
        st.tcp_collectives = [_Coll(), _Coll()]
        from horovod_tpu.common.message import ResponseList
        rl = ResponseList(tuned_fused=1)
        # The apply block from _background_loop, isolated:
        if rl.tuned_fused >= 0:
            for coll in st.tcp_collectives:
                coll.fused = bool(rl.tuned_fused)
        assert all(c.fused for c in st.tcp_collectives)
    finally:
        st.tcp_collectives = saved


# ---------------------------------------------------------------------------
# hvdlint HVD1004 fixture
# ---------------------------------------------------------------------------
def test_fixture_per_segment_codec_loop():
    from horovod_tpu.analysis.lint import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = lint_paths([os.path.join(repo, "tests", "fixtures", "lint",
                                   "backend", "codec_loop.py")])
    slugs = [v.rule.slug for v in out]
    assert slugs == ["per-segment-codec-loop"] * 4
    flagged = {v.message.split("'")[1] for v in out}
    assert flagged == {"dequantize", "from_bytes", "to_bytes",
                       "quantize"}


def test_codec_loop_rule_scope_is_backend():
    """The rule bites only in backend/ modules — compress/ itself and
    test helpers may loop over codec calls freely."""
    from horovod_tpu.analysis.lint import lint_source

    src = ("from horovod_tpu.compress import quantize\n"
           "def f(chunks, codec, bs):\n"
           "    return [quantize(c, codec, bs) for c in chunks]\n")
    hits = lint_source(src, "horovod_tpu/backend/x.py")
    assert [v.rule.slug for v in hits] == ["per-segment-codec-loop"]
    assert lint_source(src, "horovod_tpu/compress/x.py") == []
    assert lint_source(src, "horovod_tpu/common/x.py") == []


# ---------------------------------------------------------------------------
# The 4-rank 4 MiB fused-vs-reference A/B (acceptance battery)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_fused_beats_reference_4rank_4mib(kv):
    """4 ranks, 4 MiB fp32 payload through the int8 quantized plane:
    the fused single-pass kernels must beat the PR 3 pipelined
    reference chain by the ISSUE 6 acceptance floor (1.15x; measured
    2.3-2.6x at authoring time with the native hvd_qencode/hvd_qdecode
    kernels, ~1.1-1.27x on the numpy fallback), with bitwise-identical
    outputs."""
    size, n, reps = 4, 1 << 20, 5
    rng = np.random.default_rng(42)
    data = rng.standard_normal((size, n)).astype(np.float32)
    sync = threading.Barrier(size)
    timings: dict[str, list[float]] = {"reference": [], "fused": []}
    outs: dict[str, np.ndarray] = {}

    def fn(coll, r):
        for mode in ("fused", "reference", "fused", "reference"):
            coll.fused = mode == "fused"           # warm both paths
            coll.quantized_allreduce(data[r].copy(),
                                     CompressionCodec.INT8, 128)
        for mode in ("reference", "fused"):
            coll.fused = mode == "fused"
            for _ in range(reps):
                sync.wait()
                t0 = time.perf_counter()
                out = coll.quantized_allreduce(data[r].copy(),
                                               CompressionCodec.INT8,
                                               128)
                sync.wait()
                if r == 0:
                    timings[mode].append(time.perf_counter() - t0)
            if r == 0:
                outs[mode] = np.asarray(out)
        return True

    _world(kv, size, "ab4", fn, timeout=300.0)
    np.testing.assert_array_equal(outs["reference"], outs["fused"])
    ref_t = sorted(timings["reference"])[reps // 2]
    fused_t = sorted(timings["fused"])[reps // 2]
    print(f"\n4-rank 4 MiB int8 allreduce: reference {ref_t * 1e3:.1f} ms"
          f" -> fused {fused_t * 1e3:.1f} ms ({ref_t / fused_t:.2f}x)")
    assert fused_t < ref_t, (fused_t, ref_t)
    from horovod_tpu import native
    if native.available():
        # The acceptance floor holds with margin on the native kernels;
        # the numpy fallback still wins, just not by a guaranteed 1.15x
        # on arbitrarily loaded CI hosts.
        assert ref_t / fused_t >= 1.15, (fused_t, ref_t)
